"""Classification evaluation: accuracy/precision/recall/F1/confusion matrix,
top-N accuracy, FPR/FNR/false-alarm rate, label-named stats report.

Reference: `deeplearning4j-nn/.../eval/Evaluation.java:46` (precision:454,
recall:502, FPR/FNR:522-600, falseAlarmRate:619, f1:645, accuracy:659,
topNAccuracy:674, stats:352, network conveniences:160-176). Accumulation is
host-side numpy (cheap vs. the model forward); the heavy part — the model
forward producing predictions — runs on TPU.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np


@dataclass
class Prediction:
    """One example's (actual, predicted) record for error analysis
    (reference `eval/meta/Prediction.java` — used by
    `Evaluation.getPredictionErrors()` etc.)."""

    actual: int
    predicted: int
    example_index: int


class Evaluation:
    """`top_n > 1` additionally tracks top-N accuracy (reference
    `Evaluation(int topN)` constructor + `topNAccuracy():674`)."""

    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None,
                 record_meta: bool = False,
                 top_n: int = 1):
        self.num_classes = num_classes or (len(labels) if labels else None)
        self.label_names = labels
        self.record_meta = record_meta
        self.top_n = int(top_n)
        self._top_n_correct = 0
        self._top_n_total = 0
        self._predictions: List[Prediction] = []
        self._examples_seen = 0
        self._confusion: Optional[np.ndarray] = None  # [actual, predicted]

    # ------------------------------------------------------------------ acc
    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None, network=None) -> None:
        """Accumulate a batch. labels/predictions: (N, C) one-hot/probs, or
        (B, T, C) time series (flattened with mask), or (N, 1)/(N,) binary
        probabilities (thresholded at 0.5, two-class confusion — reference
        `eval`'s single-output branch).

        With `network=`, the second argument is the network INPUT and the
        predictions are computed by the network's test-mode forward
        (reference `eval(labels, input, network)` conveniences :160-176)."""
        if network is not None:
            out = network.output(predictions, train=False)
            # ComputationGraph returns one array per network output
            predictions = out[0] if isinstance(out, (list, tuple)) else out
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        # binary single-output-column case: p(class 1) thresholded at 0.5;
        # expansion keeps leading dims so (B, T, 1) sequences flow into the
        # ndim==3 flatten-with-mask path below
        if predictions.ndim == 1:
            predictions = predictions.reshape(-1, 1)
        if predictions.shape[-1] == 1:
            p1 = predictions.astype(np.float64)
            predictions = np.concatenate([1.0 - p1, p1], axis=-1)
            if labels.ndim == predictions.ndim - 1:
                labels = labels[..., None]
            if labels.shape[-1] == 1:
                l1 = labels > 0.5
                labels = np.concatenate([~l1, l1], axis=-1).astype(np.float64)
            if self.num_classes is None:
                self.num_classes = 2
        # sparse labels: integer class ids shaped predictions.shape[:-1]
        sparse = (labels.ndim == predictions.ndim - 1
                  and np.issubdtype(labels.dtype, np.integer))
        if predictions.ndim == 3:
            B, T, C = predictions.shape
            labels = (labels.reshape(B * T) if sparse
                      else labels.reshape(B * T, C))
            predictions = predictions.reshape(B * T, C)
            if mask is not None:
                mask = np.asarray(mask).reshape(B * T)
        if self.num_classes is None:
            self.num_classes = predictions.shape[-1]
        if self._confusion is None:
            self._confusion = np.zeros((self.num_classes, self.num_classes), np.int64)
        actual = labels if sparse else np.argmax(labels, axis=-1)
        pred = np.argmax(predictions, axis=-1)
        total = actual.shape[0]  # PRE-mask flattened positions
        if mask is not None:
            keep_idx = np.where(np.asarray(mask).astype(bool).reshape(-1))[0]
            actual, pred = actual[keep_idx], pred[keep_idx]
        else:
            keep_idx = np.arange(total)
        # sparse id range check AFTER mask filtering (sentinel ids on
        # masked-out positions are fine); without it, np.add.at would
        # silently wrap negatives into the last confusion row
        if sparse:
            from deeplearning4j_tpu.ops.losses import check_sparse_label_range

            check_sparse_label_range(actual, self.num_classes,
                                     where="evaluation")
        np.add.at(self._confusion, (actual, pred), 1)
        if self.top_n > 1 and predictions.shape[-1] > 1:
            kept_probs = predictions[keep_idx]
            true_prob = kept_probs[np.arange(len(actual)), actual]
            # correct iff fewer than top_n entries are STRICTLY greater
            # than the true class's probability (reference `eval:295-305`)
            n_greater = (kept_probs > true_prob[:, None]).sum(axis=-1)
            self._top_n_correct += int((n_greater < self.top_n).sum())
            self._top_n_total += len(actual)
        if self.record_meta:
            # example_index counts pre-mask flattened positions (row, or
            # b*T + t for sequences), so it maps back to the evaluated data
            # even when masked timesteps were skipped
            base = self._examples_seen
            self._predictions.extend(
                Prediction(int(a), int(p), base + int(k))
                for a, p, k in zip(actual, pred, keep_idx))
        self._examples_seen += total

    def merge(self, other: "Evaluation") -> None:
        """Accumulate another Evaluation's state into this one (reference
        `Evaluation.merge` — how distributed evaluation combines
        per-worker results)."""
        if other._confusion is None:
            return
        untouched = self._confusion is None and self._top_n_total == 0
        if other.top_n != self.top_n:
            if untouched and self.top_n == 1:
                self.top_n = other.top_n  # fresh aggregator adopts source's
            else:
                raise ValueError(f"cannot merge: top_n {self.top_n} vs "
                                 f"{other.top_n}")
        if self.label_names is None:
            self.label_names = other.label_names
        if self._confusion is None:
            self.num_classes = other.num_classes
            self._confusion = other._confusion.copy()
        else:
            if self.num_classes != other.num_classes:
                raise ValueError(
                    f"cannot merge: {self.num_classes} vs "
                    f"{other.num_classes} classes")
            self._confusion += other._confusion
        self._top_n_correct += other._top_n_correct
        self._top_n_total += other._top_n_total
        if self.record_meta and other.record_meta:
            base = self._examples_seen
            self._predictions.extend(
                Prediction(p.actual, p.predicted, base + p.example_index)
                for p in other._predictions)
        self._examples_seen += other._examples_seen

    # ----------------------------------------------------- prediction meta
    def _require_meta(self) -> None:
        if not self.record_meta:
            raise ValueError("construct Evaluation(record_meta=True) to "
                             "record per-example predictions")

    def get_prediction_errors(self) -> List[Prediction]:
        """Misclassified examples (reference
        `Evaluation.getPredictionErrors()`)."""
        self._require_meta()
        return [p for p in self._predictions if p.actual != p.predicted]

    def get_predictions_by_actual_class(self, cls: int) -> List[Prediction]:
        self._require_meta()
        return [p for p in self._predictions if p.actual == cls]

    def get_predictions_by_predicted_class(self, cls: int) -> List[Prediction]:
        self._require_meta()
        return [p for p in self._predictions if p.predicted == cls]

    # -------------------------------------------------------------- counts
    @property
    def confusion_matrix(self) -> np.ndarray:
        return self._confusion if self._confusion is not None else np.zeros((0, 0))

    def true_positives(self, cls: int) -> int:
        return int(self._confusion[cls, cls])

    def false_positives(self, cls: int) -> int:
        return int(self._confusion[:, cls].sum() - self._confusion[cls, cls])

    def false_negatives(self, cls: int) -> int:
        return int(self._confusion[cls, :].sum() - self._confusion[cls, cls])

    def true_negatives(self, cls: int) -> int:
        """Examples neither labeled nor predicted as `cls` (reference
        `trueNegatives` counter semantics)."""
        c = self._confusion
        return int(c.sum() - c[cls, :].sum() - c[:, cls].sum() + c[cls, cls])

    def class_label(self, cls: int) -> str:
        if self.label_names is not None and cls < len(self.label_names):
            return self.label_names[cls]
        return str(cls)

    # -------------------------------------------------------------- metrics
    def accuracy(self) -> float:
        if self._confusion is None:
            return 0.0
        c = self._confusion
        total = c.sum()
        return float(np.trace(c)) / total if total else 0.0

    def top_n_accuracy(self) -> float:
        """Fraction of examples whose true class was among the top-N
        predicted probabilities (reference `topNAccuracy():674`; equals
        `accuracy()` for top_n=1)."""
        if self.top_n <= 1:
            return self.accuracy()
        if self._top_n_total == 0:
            return 0.0
        return self._top_n_correct / self._top_n_total

    def _avg_excluding_edge(self, per_class) -> float:
        """Macro-average of a per-class metric, excluding classes whose
        metric is undefined (0/0 — reference's `-1` edge-case sentinel
        exclusion in `precision()`/`recall()`/`falsePositiveRate()`)."""
        vals = [per_class(i) for i in range(self.num_classes)]
        vals = [v for v in vals if v is not None]
        return float(np.mean(vals)) if vals else 0.0

    def precision(self, cls: Optional[int] = None, edge: float = 0.0) -> float:
        if self._confusion is None:
            return 0.0
        if cls is not None:
            tp, fp = self.true_positives(cls), self.false_positives(cls)
            return tp / (tp + fp) if (tp + fp) else edge
        return self._avg_excluding_edge(
            lambda i: self.precision(i) if (self.true_positives(i)
                                            + self.false_positives(i)) else None)

    def recall(self, cls: Optional[int] = None, edge: float = 0.0) -> float:
        if self._confusion is None:
            return 0.0
        if cls is not None:
            tp, fn = self.true_positives(cls), self.false_negatives(cls)
            return tp / (tp + fn) if (tp + fn) else edge
        return self._avg_excluding_edge(
            lambda i: self.recall(i) if (self.true_positives(i)
                                         + self.false_negatives(i)) else None)

    def false_positive_rate(self, cls: Optional[int] = None,
                            edge: float = 0.0) -> float:
        """FP / (FP + TN); class average excludes undefined classes
        (reference `falsePositiveRate:522`)."""
        if self._confusion is None:
            return 0.0
        if cls is not None:
            fp, tn = self.false_positives(cls), self.true_negatives(cls)
            return fp / (fp + tn) if (fp + tn) else edge
        return self._avg_excluding_edge(
            lambda i: self.false_positive_rate(i)
            if (self.false_positives(i) + self.true_negatives(i)) else None)

    def false_negative_rate(self, cls: Optional[int] = None,
                            edge: float = 0.0) -> float:
        """FN / (FN + TP) (reference `falseNegativeRate:560`)."""
        if self._confusion is None:
            return 0.0
        if cls is not None:
            fn, tp = self.false_negatives(cls), self.true_positives(cls)
            return fn / (fn + tp) if (fn + tp) else edge
        return self._avg_excluding_edge(
            lambda i: self.false_negative_rate(i)
            if (self.false_negatives(i) + self.true_positives(i)) else None)

    def false_alarm_rate(self) -> float:
        """(FPR + FNR) / 2 (reference `falseAlarmRate():619`)."""
        return (self.false_positive_rate() + self.false_negative_rate()) / 2.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    # --------------------------------------------------------------- report
    def stats(self, suppress_warnings: bool = False) -> str:
        """Multi-line classification report: label-named confusion lines,
        excluded-class warnings, and the scores block (reference
        `stats():352-408`)."""
        if self._confusion is None:
            return "Evaluation: no examples seen"
        lines: List[str] = []
        warnings: List[str] = []
        for a in range(self.num_classes):
            for p in range(self.num_classes):
                count = int(self._confusion[a, p])
                if count:
                    lines.append(
                        f"Examples labeled as {self.class_label(a)} "
                        f"classified by model as {self.class_label(p)}: "
                        f"{count} times")
            if not suppress_warnings and self.true_positives(a) == 0:
                if self.false_positives(a) == 0:
                    warnings.append(
                        f"Warning: class {self.class_label(a)} was never "
                        "predicted by the model. This class was excluded "
                        "from the average precision")
                if self.false_negatives(a) == 0:
                    warnings.append(
                        f"Warning: class {self.class_label(a)} has never "
                        "appeared as a true label. This class was excluded "
                        "from the average recall")
        lines.append("")
        lines.extend(warnings)
        lines += [
            "==========================Scores========================================",
            f" Accuracy:  {self.accuracy():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f" Top {self.top_n} Accuracy:  "
                         f"{self.top_n_accuracy():.4f}")
        lines += [
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "========================================================================",
        ]
        return "\n".join(lines)
