"""Classification evaluation: accuracy/precision/recall/F1/confusion matrix.

Reference: `deeplearning4j-nn/.../eval/Evaluation.java:46` (precision:454,
recall:502, f1:645, accuracy:659, confusion matrix). Accumulation is
host-side numpy (cheap vs. the model forward); the heavy part — the model
forward producing predictions — runs on TPU.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np


@dataclass
class Prediction:
    """One example's (actual, predicted) record for error analysis
    (reference `eval/meta/Prediction.java` — used by
    `Evaluation.getPredictionErrors()` etc.)."""

    actual: int
    predicted: int
    example_index: int


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None,
                 record_meta: bool = False):
        self.num_classes = num_classes or (len(labels) if labels else None)
        self.label_names = labels
        self.record_meta = record_meta
        self._predictions: List[Prediction] = []
        self._examples_seen = 0
        self._confusion: Optional[np.ndarray] = None  # [actual, predicted]

    # ------------------------------------------------------------------ acc
    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        """Accumulate a batch. labels/predictions: (N, C) one-hot/probs, or
        (B, T, C) time series (flattened with mask)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        # sparse labels: integer class ids shaped predictions.shape[:-1]
        sparse = (labels.ndim == predictions.ndim - 1
                  and np.issubdtype(labels.dtype, np.integer))
        if predictions.ndim == 3:
            B, T, C = predictions.shape
            labels = (labels.reshape(B * T) if sparse
                      else labels.reshape(B * T, C))
            predictions = predictions.reshape(B * T, C)
            if mask is not None:
                mask = np.asarray(mask).reshape(B * T)
        if self.num_classes is None:
            self.num_classes = predictions.shape[-1]
        if self._confusion is None:
            self._confusion = np.zeros((self.num_classes, self.num_classes), np.int64)
        actual = labels if sparse else np.argmax(labels, axis=-1)
        pred = np.argmax(predictions, axis=-1)
        total = actual.shape[0]  # PRE-mask flattened positions
        if mask is not None:
            keep_idx = np.where(np.asarray(mask).astype(bool).reshape(-1))[0]
            actual, pred = actual[keep_idx], pred[keep_idx]
        else:
            keep_idx = np.arange(total)
        # sparse id range check AFTER mask filtering (sentinel ids on
        # masked-out positions are fine); without it, np.add.at would
        # silently wrap negatives into the last confusion row
        if sparse:
            from deeplearning4j_tpu.ops.losses import check_sparse_label_range

            check_sparse_label_range(actual, self.num_classes,
                                     where="evaluation")
        np.add.at(self._confusion, (actual, pred), 1)
        if self.record_meta:
            # example_index counts pre-mask flattened positions (row, or
            # b*T + t for sequences), so it maps back to the evaluated data
            # even when masked timesteps were skipped
            base = self._examples_seen
            self._predictions.extend(
                Prediction(int(a), int(p), base + int(k))
                for a, p, k in zip(actual, pred, keep_idx))
        self._examples_seen += total

    # ----------------------------------------------------- prediction meta
    def _require_meta(self) -> None:
        if not self.record_meta:
            raise ValueError("construct Evaluation(record_meta=True) to "
                             "record per-example predictions")

    def get_prediction_errors(self) -> List[Prediction]:
        """Misclassified examples (reference
        `Evaluation.getPredictionErrors()`)."""
        self._require_meta()
        return [p for p in self._predictions if p.actual != p.predicted]

    def get_predictions_by_actual_class(self, cls: int) -> List[Prediction]:
        self._require_meta()
        return [p for p in self._predictions if p.actual == cls]

    def get_predictions_by_predicted_class(self, cls: int) -> List[Prediction]:
        self._require_meta()
        return [p for p in self._predictions if p.predicted == cls]

    # -------------------------------------------------------------- metrics
    @property
    def confusion_matrix(self) -> np.ndarray:
        return self._confusion if self._confusion is not None else np.zeros((0, 0))

    def true_positives(self, cls: int) -> int:
        return int(self._confusion[cls, cls])

    def false_positives(self, cls: int) -> int:
        return int(self._confusion[:, cls].sum() - self._confusion[cls, cls])

    def false_negatives(self, cls: int) -> int:
        return int(self._confusion[cls, :].sum() - self._confusion[cls, cls])

    def accuracy(self) -> float:
        if self._confusion is None:
            return 0.0
        c = self._confusion
        total = c.sum()
        return float(np.trace(c)) / total if total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        if self._confusion is None:
            return 0.0
        if cls is not None:
            tp, fp = self.true_positives(cls), self.false_positives(cls)
            return tp / (tp + fp) if (tp + fp) else 0.0
        vals = [self.precision(i) for i in range(self.num_classes)
                if self._confusion[:, i].sum() > 0 or self._confusion[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if self._confusion is None:
            return 0.0
        if cls is not None:
            tp, fn = self.true_positives(cls), self.false_negatives(cls)
            return tp / (tp + fn) if (tp + fn) else 0.0
        vals = [self.recall(i) for i in range(self.num_classes)
                if self._confusion[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def stats(self) -> str:
        lines = [
            "==========================Scores========================================",
            f" Accuracy:  {self.accuracy():.4f}",
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "========================================================================",
        ]
        return "\n".join(lines)
