"""Regression evaluation (reference `eval/RegressionEvaluation.java`):
per-column MSE / MAE / RMSE / RSE / correlation / R2."""
from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None):
        self.n = 0
        self.n_columns = n_columns
        self._sum_sq_err = None
        self._sum_abs_err = None
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_pred_sq = None
        self._sum_label_pred = None

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                keep = np.asarray(mask).astype(bool).reshape(-1)
                labels, predictions = labels[keep], predictions[keep]
        if self._sum_sq_err is None:
            self.n_columns = labels.shape[-1]
            z = lambda: np.zeros(self.n_columns)
            self._sum_sq_err, self._sum_abs_err = z(), z()
            self._sum_label, self._sum_label_sq = z(), z()
            self._sum_pred, self._sum_pred_sq, self._sum_label_pred = z(), z(), z()
        err = predictions - labels
        self._sum_sq_err += (err**2).sum(0)
        self._sum_abs_err += np.abs(err).sum(0)
        self._sum_label += labels.sum(0)
        self._sum_label_sq += (labels**2).sum(0)
        self._sum_pred += predictions.sum(0)
        self._sum_pred_sq += (predictions**2).sum(0)
        self._sum_label_pred += (labels * predictions).sum(0)
        self.n += labels.shape[0]

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self._sum_sq_err[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self._sum_abs_err[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self._sum_sq_err[col] / self.n))

    def relative_squared_error(self, col: int = 0) -> float:
        """RSE = Σ(pred - label)² / Σ(label - mean_label)² (reference
        `RegressionEvaluation.relativeSquaredError`)."""
        n = self.n
        denom = self._sum_label_sq[col] - self._sum_label[col] ** 2 / n
        return float(self._sum_sq_err[col] / max(denom, 1e-12))

    def correlation_r2(self, col: int = 0) -> float:
        n = self.n
        sx, sy = self._sum_label[col], self._sum_pred[col]
        sxx, syy = self._sum_label_sq[col], self._sum_pred_sq[col]
        sxy = self._sum_label_pred[col]
        num = n * sxy - sx * sy
        den = np.sqrt(max(n * sxx - sx**2, 1e-12)) * np.sqrt(max(n * syy - sy**2, 1e-12))
        r = num / den
        return float(r * r)

    def stats(self) -> str:
        cols = range(self.n_columns or 0)
        lines = ["Column    MSE            MAE            RMSE           R^2"]
        for c in cols:
            lines.append(f"{c:<9} {self.mean_squared_error(c):<14.6f} "
                         f"{self.mean_absolute_error(c):<14.6f} "
                         f"{self.root_mean_squared_error(c):<14.6f} "
                         f"{self.correlation_r2(c):<14.6f}")
        return "\n".join(lines)
