"""ROC / AUC, thresholded accumulation.

Reference: `eval/ROC.java` (296 LoC, thresholded counts at K steps) and
`ROCMultiClass.java` — same thresholded design so streaming batches
accumulate O(K) state rather than storing every score.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def _dedup_trapezoid(x: np.ndarray, y: np.ndarray) -> float:
    """Trapezoidal area under (x, y) points after collapsing duplicate x
    values to their max y (best operating point at that x)."""
    best: dict = {}
    for xi, yi in zip(x, y):
        best[float(xi)] = max(best.get(float(xi), 0.0), float(yi))
    xs = np.array(sorted(best))
    ys = np.array([best[xi] for xi in xs])
    return float(abs(np.trapezoid(ys, xs)))


class ROC:
    """Binary ROC. Labels: (N,) {0,1} or (N,2) one-hot; probs likewise."""

    def __init__(self, threshold_steps: int = 100):
        self.steps = threshold_steps
        self.thresholds = np.linspace(0.0, 1.0, threshold_steps + 1)
        self._tp = np.zeros(threshold_steps + 1, np.int64)
        self._fp = np.zeros(threshold_steps + 1, np.int64)
        self._pos = 0
        self._neg = 0

    def eval(self, labels: np.ndarray, probs: np.ndarray) -> None:
        labels = np.asarray(labels)
        probs = np.asarray(probs)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
            probs = probs[:, 1]
        labels = labels.reshape(-1).astype(bool)
        probs = probs.reshape(-1)
        for i, t in enumerate(self.thresholds):
            pred = probs >= t
            self._tp[i] += int(np.sum(pred & labels))
            self._fp[i] += int(np.sum(pred & ~labels))
        self._pos += int(labels.sum())
        self._neg += int((~labels).sum())

    def get_roc_curve(self):
        tpr = self._tp / max(self._pos, 1)
        fpr = self._fp / max(self._neg, 1)
        return fpr, tpr

    def get_precision_recall_curve(self):
        """(thresholds, precision, recall) at each threshold step
        (reference `ROC.getPrecisionRecallCurve` — the repo exposes the
        same thresholded counts as a PR curve alongside the ROC curve).
        Precision at thresholds with zero predicted positives is defined
        as 1.0 (nothing claimed, nothing wrong)."""
        predicted_pos = self._tp + self._fp
        precision = np.where(predicted_pos > 0,
                             self._tp / np.maximum(predicted_pos, 1), 1.0)
        recall = self._tp / max(self._pos, 1)
        return self.thresholds, precision, recall

    def calculate_auc(self) -> float:
        """Trapezoidal AUC keeping the best TPR at each distinct FPR —
        several thresholds can share an FPR (coarse threshold grids on
        well-separated scores), and the curve's value there is the best
        operating point, not whichever threshold sorted last."""
        fpr, tpr = self.get_roc_curve()
        return _dedup_trapezoid(fpr, tpr)

    def calculate_auprc(self) -> float:
        """Area under the precision-recall curve: trapezoidal over the
        thresholded points, recall-ordered, keeping the BEST precision at
        each distinct recall level (several thresholds can share a recall;
        the curve's value there is the best operating point)."""
        _, precision, recall = self.get_precision_recall_curve()
        return _dedup_trapezoid(recall, precision)


class ROCMultiClass:
    """One-vs-all ROC per class (reference `eval/ROCMultiClass.java`)."""

    def __init__(self, threshold_steps: int = 100):
        self.steps = threshold_steps
        self._rocs = {}

    def eval(self, labels: np.ndarray, probs: np.ndarray) -> None:
        labels = np.asarray(labels)
        probs = np.asarray(probs)
        for c in range(labels.shape[-1]):
            self._rocs.setdefault(c, ROC(self.steps)).eval(labels[:, c], probs[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs.values()]))
