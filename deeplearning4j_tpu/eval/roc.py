"""ROC / AUC, thresholded accumulation.

Reference: `eval/ROC.java` (296 LoC, thresholded counts at K steps) and
`ROCMultiClass.java` — same thresholded design so streaming batches
accumulate O(K) state rather than storing every score.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class ROC:
    """Binary ROC. Labels: (N,) {0,1} or (N,2) one-hot; probs likewise."""

    def __init__(self, threshold_steps: int = 100):
        self.steps = threshold_steps
        self.thresholds = np.linspace(0.0, 1.0, threshold_steps + 1)
        self._tp = np.zeros(threshold_steps + 1, np.int64)
        self._fp = np.zeros(threshold_steps + 1, np.int64)
        self._pos = 0
        self._neg = 0

    def eval(self, labels: np.ndarray, probs: np.ndarray) -> None:
        labels = np.asarray(labels)
        probs = np.asarray(probs)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
            probs = probs[:, 1]
        labels = labels.reshape(-1).astype(bool)
        probs = probs.reshape(-1)
        for i, t in enumerate(self.thresholds):
            pred = probs >= t
            self._tp[i] += int(np.sum(pred & labels))
            self._fp[i] += int(np.sum(pred & ~labels))
        self._pos += int(labels.sum())
        self._neg += int((~labels).sum())

    def get_roc_curve(self):
        tpr = self._tp / max(self._pos, 1)
        fpr = self._fp / max(self._neg, 1)
        return fpr, tpr

    def calculate_auc(self) -> float:
        fpr, tpr = self.get_roc_curve()
        order = np.argsort(fpr, kind="stable")
        return float(abs(np.trapezoid(tpr[order], fpr[order])))


class ROCMultiClass:
    """One-vs-all ROC per class (reference `eval/ROCMultiClass.java`)."""

    def __init__(self, threshold_steps: int = 100):
        self.steps = threshold_steps
        self._rocs = {}

    def eval(self, labels: np.ndarray, probs: np.ndarray) -> None:
        labels = np.asarray(labels)
        probs = np.asarray(probs)
        for c in range(labels.shape[-1]):
            self._rocs.setdefault(c, ROC(self.steps)).eval(labels[:, c], probs[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs.values()]))
