"""EvaluationTools: self-contained HTML report export (reference
`deeplearning4j-core/.../evaluation/EvaluationTools.java` —
`exportRocChartsToHtmlFile` / evaluation reports rendered via the
ui-components chart DSL; here inline SVG, zero external assets)."""
from __future__ import annotations

from pathlib import Path
from typing import Union

import numpy as np

from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.eval.roc import ROC


def _svg_line_chart(xs, ys, title: str, w: int = 480, h: int = 360,
                    diagonal: bool = False) -> str:
    pad = 40
    xs, ys = np.asarray(xs, float), np.asarray(ys, float)

    def sx(x):
        return pad + x * (w - 2 * pad)

    def sy(y):
        return h - pad - y * (h - 2 * pad)

    pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys))
    diag = (f'<line x1="{sx(0):.1f}" y1="{sy(0):.1f}" x2="{sx(1):.1f}" '
            f'y2="{sy(1):.1f}" stroke="#bbb" stroke-dasharray="4"/>'
            if diagonal else "")
    return f"""<svg width="{w}" height="{h}" style="border:1px solid #ccc">
<text x="{w / 2}" y="20" text-anchor="middle" font-weight="bold">{title}</text>
<line x1="{pad}" y1="{h - pad}" x2="{w - pad}" y2="{h - pad}" stroke="#333"/>
<line x1="{pad}" y1="{pad}" x2="{pad}" y2="{h - pad}" stroke="#333"/>
{diag}
<polyline points="{pts}" fill="none" stroke="#1f77b4" stroke-width="1.5"/>
</svg>"""


class EvaluationTools:
    @staticmethod
    def roc_chart_html(roc: ROC) -> str:
        fpr, tpr = roc.get_roc_curve()
        auc = roc.calculate_auc()
        chart = _svg_line_chart(fpr, tpr, f"ROC (AUC = {auc:.4f})",
                                diagonal=True)
        return (f"<html><head><title>ROC</title></head><body>"
                f"<h1>ROC curve</h1>{chart}</body></html>")

    @staticmethod
    def export_roc_charts_to_html_file(roc: ROC,
                                       path: Union[str, Path]) -> None:
        Path(path).write_text(EvaluationTools.roc_chart_html(roc),
                              encoding="utf-8")

    @staticmethod
    def evaluation_html(ev: Evaluation) -> str:
        cm = ev.confusion_matrix
        n = cm.shape[0]
        rows = "".join(
            "<tr><th>{}</th>{}</tr>".format(
                i, "".join(f"<td>{int(cm[i, j])}</td>" for j in range(n)))
            for i in range(n))
        header = "".join(f"<th>{j}</th>" for j in range(n))
        return f"""<html><head><title>Evaluation</title>
<style>table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #999; padding: 4px 8px; text-align: right; }}</style>
</head><body>
<h1>Evaluation</h1>
<ul>
<li>Accuracy: {ev.accuracy():.4f}</li>
<li>Precision: {ev.precision():.4f}</li>
<li>Recall: {ev.recall():.4f}</li>
<li>F1: {ev.f1():.4f}</li>
</ul>
<h2>Confusion matrix (rows = actual)</h2>
<table><tr><th></th>{header}</tr>{rows}</table>
</body></html>"""

    @staticmethod
    def export_evaluation_to_html_file(ev: Evaluation,
                                       path: Union[str, Path]) -> None:
        Path(path).write_text(EvaluationTools.evaluation_html(ev),
                              encoding="utf-8")
