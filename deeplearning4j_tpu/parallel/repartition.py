"""Repartitioning policy for distributed training windows.

TPU-native equivalent of the reference's Spark repartition plumbing
(`spark/api/Repartition.java`, `spark/api/RepartitionStrategy.java`,
`spark/impl/common/repartition/BalancedPartitioner.java`,
`SparkUtils.repartition` called from
`ParameterAveragingTrainingMaster.doIteration:654`): decide whether the
minibatches of an averaging window should be redistributed across workers,
and if so produce partitions whose sizes differ by at most one.

Here the "RDD" is a plain list of host-side DataSets (device placement
happens inside the jitted step), so repartitioning is a cheap in-memory
shuffle rather than a cluster-wide data movement — but the policy surface
is preserved so TrainingMaster configs translate directly.
"""
from __future__ import annotations

import enum
from typing import List, Optional, Sequence, TypeVar

import numpy as np

T = TypeVar("T")


class Repartition(str, enum.Enum):
    """When to repartition (reference `spark/api/Repartition.java`)."""

    NEVER = "never"
    ALWAYS = "always"
    NUM_PARTITIONS_WORKERS_DIFFERS = "num_partitions_workers_differs"


class RepartitionStrategy(str, enum.Enum):
    """How to repartition (reference `spark/api/RepartitionStrategy.java`:
    SparkDefault vs Balanced). ROUND_ROBIN is the cheap default (keeps
    arrival order, deterministic); BALANCED additionally randomizes which
    partitions get the +1 remainder element (reference
    `BalancedPartitioner` assigns the remainder uniformly at random)."""

    ROUND_ROBIN = "round_robin"
    BALANCED = "balanced"


def should_repartition(num_items: int, num_partitions: int,
                       repartition: Repartition) -> bool:
    """Policy gate (reference `SparkUtils.repartition` switch)."""
    if repartition == Repartition.NEVER:
        return False
    if repartition == Repartition.ALWAYS:
        return True
    # NUM_PARTITIONS_WORKERS_DIFFERS: only when an even round-robin split
    # would leave partition sizes unequal
    return num_items % num_partitions != 0


def balanced_partitions(items: Sequence[T], num_partitions: int,
                        strategy: RepartitionStrategy = RepartitionStrategy.ROUND_ROBIN,
                        seed: Optional[int] = None) -> List[List[T]]:
    """Split `items` into `num_partitions` lists whose sizes differ by at
    most one (reference `BalancedPartitioner`: elementsPerPartition =
    ceil/floor split with the remainder spread one-each). Empty partitions
    are dropped, matching the reference's tolerance for short splits
    (`ParameterAveragingTrainingMaster.java:801`)."""
    if num_partitions < 1:
        raise ValueError("num_partitions must be >= 1")
    n = len(items)
    if strategy == RepartitionStrategy.ROUND_ROBIN:
        parts = [list(items[i::num_partitions]) for i in range(num_partitions)]
        return [p for p in parts if p]
    # BALANCED: contiguous floor-size chunks, remainder elements handed to a
    # random subset of partitions (reference BalancedPartitioner.getPartition
    # uniform remainder assignment)
    base, rem = divmod(n, num_partitions)
    rng = np.random.default_rng(seed)
    extra = set(rng.choice(num_partitions, size=rem, replace=False)) if rem else set()
    parts, pos = [], 0
    for p in range(num_partitions):
        size = base + (1 if p in extra else 0)
        if size:
            parts.append(list(items[pos:pos + size]))
        pos += size
    return parts
