"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO long-context mechanism beyond truncated BPTT
(`MultiLayerNetwork.doTruncatedBPTT`, `MultiLayerNetwork.java:1140-1194`) and
no parallelism besides data-parallel (`SURVEY.md` §2.4) — the model and the
full sequence must fit on one device. This module is the TPU-native answer:
shard the TIME axis of attention across a `seq` mesh axis so context length
scales with chip count.

Two strategies, both built on `shard_map` + XLA collectives over ICI:

- **Ring attention** (`ring_attention`): each device keeps its Q shard
  resident and rotates K/V shards around the ring with `lax.ppermute`,
  folding each visiting block into the flash-attention online-softmax
  accumulator (`ops/attention.py`). Communication is neighbor-to-neighbor —
  exactly the ICI topology — and each hop's transfer overlaps the matmul on
  the block already in hand (the ppermute for step i+1 is issued before the
  step-i compute, letting XLA run the DMA concurrently).

- **Ulysses all-to-all** (`ulysses_attention`): `lax.all_to_all` reshards
  (T/n, H) → (T, H/n), runs full attention on complete sequences for the
  local head subset, then reshards back. Two all-to-alls per call; wins when
  H ≥ n_devices and per-device memory fits T·H/n.

Both are exact: parity with single-device full attention is tested on the
virtual 8-device CPU mesh (`tests/test_attention.py`), the same
validate-distributed-without-a-cluster strategy the reference uses for Spark
(`BaseSparkTest.java:89-90`).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

from deeplearning4j_tpu.ops.attention import (
    NEG_INF,
    _accum_init,
    attention_block_accum,
    attention_finalize,
    mask_bias,
)


def _ring_attention_local(q, k, v, key_mask, *, axis_name: str, n_shards: int,
                          causal: bool):
    """Per-device body under shard_map. q/k/v: the LOCAL time shard
    (B, T_local, H, D); key_mask: (B, T_local) or None. Device i owns global
    positions [i·T_local, (i+1)·T_local)."""
    idx = lax.axis_index(axis_name)
    Tl = q.shape[1]
    iq_local = jnp.arange(Tl)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    carry = _accum_init(q)
    # no mask → don't rotate a dummy mask through every ppermute hop
    kv = (k, v) if key_mask is None else (k, v, key_mask)
    for step in range(n_shards):
        # block currently held arrived from device (idx - step): issue the
        # rotation for the NEXT step first so the ppermute DMA overlaps the
        # block matmul below
        kv_next = jax.tree.map(
            lambda a: lax.ppermute(a, axis_name, perm), kv) \
            if step < n_shards - 1 else kv
        k_blk, v_blk = kv[0], kv[1]
        src = (idx - step) % n_shards
        bias = None if key_mask is None else mask_bias(kv[2])
        if causal:
            q_pos = idx * Tl + iq_local  # global query positions
            k_pos = src * Tl + jnp.arange(Tl)
            cb = jnp.where(k_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF)
            cb = cb[None, None, :, :]
            bias = cb if bias is None else bias + cb
        carry = attention_block_accum(carry, q, k_blk, v_blk, bias)
        kv = kv_next
    o, l, _ = carry
    return attention_finalize(o, l)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, *, axis_name: str = "seq",
                   causal: bool = False,
                   key_mask: Optional[jnp.ndarray] = None,
                   batch_axis: Optional[str] = None) -> jnp.ndarray:
    """Exact attention with the time axis sharded over `axis_name`.

    q/k/v are GLOBAL arrays (B, T, H, D); T must divide by the axis size.
    Returns the global (B, T, H, D) output (sharding propagated by jit when
    called inside a pjit-ted step). `batch_axis` optionally also shards B
    (dp × sp meshes).
    """
    n = mesh.shape[axis_name]
    if q.shape[1] % n != 0:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by "
                         f"mesh axis '{axis_name}' size {n}")
    bspec = batch_axis
    spec = P(bspec, axis_name, None, None)
    fn = partial(_ring_attention_local, axis_name=axis_name, n_shards=n,
                 causal=causal)
    if key_mask is None:
        return shard_map(lambda qq, kk, vv: fn(qq, kk, vv, None), mesh=mesh,
                         in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
    mask_spec = P(bspec, axis_name)
    return shard_map(fn, mesh=mesh,
                     in_specs=(spec, spec, spec, mask_spec),
                     out_specs=spec, check_vma=False)(q, k, v, key_mask)


def _ulysses_local(q, k, v, key_mask, *, axis_name: str, causal: bool):
    """Per-device body: all-to-all from time-sharded to head-sharded, full
    attention over the complete sequence for H/n heads, all-to-all back."""
    from deeplearning4j_tpu.ops.attention import full_attention

    # (B, T/n, H, D) → (B, T, H/n, D): split heads across devices, gather time
    qg = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if key_mask is None:  # skip the mask all-gather + zero bias entirely
        bias = None
    else:
        mask_g = lax.all_gather(key_mask, axis_name, axis=1, tiled=True)
        bias = mask_bias(mask_g)
    out = full_attention(qg, kg, vg, bias=bias, causal=causal)
    # back: (B, T, H/n, D) → (B, T/n, H, D)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, *, axis_name: str = "seq",
                      causal: bool = False,
                      key_mask: Optional[jnp.ndarray] = None,
                      batch_axis: Optional[str] = None) -> jnp.ndarray:
    """DeepSpeed-Ulysses-style sequence parallelism via two all-to-alls.
    Requires n_heads % axis_size == 0."""
    n = mesh.shape[axis_name]
    H = q.shape[2]
    if H % n != 0:
        raise ValueError(f"n_heads {H} not divisible by mesh axis "
                         f"'{axis_name}' size {n} (use ring_attention)")
    if q.shape[1] % n != 0:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by "
                         f"mesh axis '{axis_name}' size {n}")
    bspec = batch_axis
    spec = P(bspec, axis_name, None, None)
    fn = partial(_ulysses_local, axis_name=axis_name, causal=causal)
    if key_mask is None:
        return shard_map(lambda qq, kk, vv: fn(qq, kk, vv, None), mesh=mesh,
                         in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
    mask_spec = P(bspec, axis_name)
    return shard_map(fn, mesh=mesh,
                     in_specs=(spec, spec, spec, mask_spec),
                     out_specs=spec, check_vma=False)(q, k, v, key_mask)


class SequenceParallelWrapper(ParallelWrapper):
    """Train a SelfAttention/Transformer network with the TIME axis sharded
    over the mesh — the context-parallel training loop (sequences longer
    than one chip's HBM).

    Subclasses ParallelWrapper so the whole training loop (batch trimming,
    tBPTT guard, listener/epoch bookkeeping) is shared; the overrides are
    the batch shardings — features and labels on P(data, seq), trailing
    dims replicated, so one-hot (B, T, V) and sparse-id (B, T) labels
    both shard — and the step wrapper that opens
    `sequence_parallel_scope`, so every attention layer traced inside the
    jitted step computes via ring attention (KV blocks rotating over ICI).

    Loss curves match single-chip training up to f32 summation-order noise
    (same-seed parity test, `tests/test_transformer.py`). Masked sequences
    are not supported yet. Parameters are replicated (combine with tp via
    param_specs if needed)."""

    def __init__(self, net, mesh: Mesh, seq_axis: str = "seq",
                 data_axis: str = "data", param_specs=None):
        if seq_axis not in mesh.shape:
            raise ValueError(f"mesh has no '{seq_axis}' axis: "
                             f"{dict(mesh.shape)}")
        if data_axis not in mesh.shape and mesh.shape.get(seq_axis) != \
                int(np.prod(list(mesh.shape.values()))):
            raise ValueError(
                f"data_axis {data_axis!r} not in mesh {dict(mesh.shape)}; "
                "for a pure-sequence mesh make the seq axis span all devices")
        self.seq_axis = seq_axis
        super().__init__(net, mesh=mesh, data_axis=data_axis,
                         param_specs=param_specs)

    def _wrap_step(self, step):
        from deeplearning4j_tpu.ops.attention import sequence_parallel_scope

        d = self.data_axis if self.data_axis in self.mesh.shape else None

        def scoped_step(params, upd, lstate, it, f, l, fm, lm):
            # the scope must be open at TRACE time (first call), which is
            # why it wraps the step body rather than the jit() call
            with sequence_parallel_scope(self.mesh, self.seq_axis, d):
                return step(params, upd, lstate, it, f, l, fm, lm)

        return scoped_step

    def _batch_shardings(self):
        from jax.sharding import NamedSharding

        d = self.data_axis if self.data_axis in self.mesh.shape else None
        # P(d, seq) leaves any trailing dims replicated, so one spec serves
        # both one-hot (B, T, V) and sparse-id (B, T) labels/features
        feat = NamedSharding(self.mesh, P(d, self.seq_axis))
        lab = NamedSharding(self.mesh, P(d, self.seq_axis))
        return (feat, lab, self._repl, self._repl)

    def _shard_batch(self, ds):
        if ds.features_mask is not None or ds.labels_mask is not None:
            raise NotImplementedError(
                "masked sequences under sequence parallelism are not "
                "supported yet")
        n_seq = self.mesh.shape[self.seq_axis]
        if ds.features.shape[1] % n_seq:
            raise ValueError(
                f"sequence length {ds.features.shape[1]} not divisible by "
                f"the '{self.seq_axis}' mesh axis size {n_seq}")
        return super()._shard_batch(ds)
