"""ParallelWrapper CLI entry point.

Reference: `deeplearning4j-scaleout-parallelwrapper/.../parallelism/main/
ParallelWrapperMain.java` — JCommander flags `--modelPath
--dataSetIteratorFactoryClazz --workers --avgFrequency --uiUrl`, loads a
serialized model, builds the iterator via a factory class, trains, saves.

Usage:
    python -m deeplearning4j_tpu.parallel.main \
        --model-path model.zip --data-factory mypkg.mymod:make_iterator \
        --epochs 2 --output-path trained.zip [--mode wrapper|averaging|ps]
"""
from __future__ import annotations

import argparse
import importlib
import logging
import sys


def _load_factory(spec: str):
    """'package.module:callable' → iterator factory (reference
    `dataSetIteratorFactoryClazz`)."""
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise ValueError(
            f"--data-factory must be 'module:callable', got {spec!r}")
    mod = importlib.import_module(mod_name)
    return getattr(mod, fn_name)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.parallel.main",
        description="Multi-chip training driver (ParallelWrapperMain)")
    p.add_argument("--model-path", required=True,
                   help="serialized model zip (ModelSerializer format)")
    p.add_argument("--data-factory", required=True,
                   help="'module:callable' returning a DataSetIterator")
    p.add_argument("--output-path", required=True,
                   help="where to write the trained model zip")
    p.add_argument("--mode", choices=("wrapper", "averaging", "ps"),
                   default="wrapper",
                   help="wrapper = pjit/ICI sharded step (default); "
                        "averaging = TrainingMaster parameter averaging; "
                        "ps = async parameter server")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--workers", type=int, default=2,
                   help="worker count for averaging/ps modes")
    p.add_argument("--avg-frequency", type=int, default=5,
                   help="averaging window (averaging/ps sync frequency)")
    p.add_argument("--ui-url", default=None,
                   help="remote UI endpoint for stats routing (host:port)")
    return p


def run(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from deeplearning4j_tpu.util.serialization import (
        restore_model,
        write_model,
    )

    net = restore_model(args.model_path)
    iterator = _load_factory(args.data_factory)()

    if args.ui_url:
        from deeplearning4j_tpu.ui.remote import RemoteUIStatsStorageRouter
        from deeplearning4j_tpu.ui.stats_listener import StatsListener
        router = RemoteUIStatsStorageRouter(f"http://{args.ui_url}")
        net.set_listeners(*(net.listeners + [StatsListener(router)]))

    if args.mode == "wrapper":
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        ParallelWrapper(net).fit(iterator, epochs=args.epochs)
    elif args.mode == "averaging":
        from deeplearning4j_tpu.parallel.training_master import (
            DistributedMultiLayer,
            ParameterAveragingTrainingMaster,
        )
        master = ParameterAveragingTrainingMaster(
            num_workers=args.workers,
            averaging_frequency=args.avg_frequency)
        DistributedMultiLayer(net, master).fit(iterator, epochs=args.epochs)
    else:
        from deeplearning4j_tpu.parallel.parameter_server import (
            ParameterServerParallelWrapper,
        )
        ParameterServerParallelWrapper(
            net, workers=args.workers,
            sync_frequency=args.avg_frequency).fit(iterator,
                                                   epochs=args.epochs)

    write_model(net, args.output_path)
    logging.getLogger("deeplearning4j_tpu").info(
        "trained model written to %s (final score %.5f)",
        args.output_path, net.score_value or float("nan"))
    return 0


if __name__ == "__main__":
    sys.exit(run())
