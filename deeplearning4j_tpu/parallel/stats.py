"""Per-phase training stats for distributed masters.

Reference: `dl4j-spark/.../spark/api/stats/SparkTrainingStats.java`,
`CommonSparkTrainingStats.java`, and
`paramavg/stats/ParameterAveragingTrainingMasterStats.java` — wall-clock per
phase (split / fit / aggregate / broadcast), keyed timing lists.
"""
from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List


class TrainingStats:
    """Phase wall-clock collection (ms per occurrence) plus named event
    counters (worker failures / retries / drops / restarts — the elastic
    layer's observability surface; the reference's stats classes only track
    timings because Spark owns its retry bookkeeping)."""

    def __init__(self) -> None:
        self._times: Dict[str, List[float]] = defaultdict(list)
        self._counters: Dict[str, int] = defaultdict(int)

    def add_time(self, phase: str, ms: float) -> None:
        self._times[phase].append(ms)

    def timer(self, phase: str) -> "_PhaseTimer":
        return _PhaseTimer(self, phase)

    def increment(self, counter: str, by: int = 1) -> None:
        self._counters[counter] += by

    def get_count(self, counter: str) -> int:
        return self._counters.get(counter, 0)

    def get_counters(self) -> Dict[str, int]:
        return dict(self._counters)

    def get_keys(self) -> List[str]:
        return sorted(self._times)

    def get_value(self, phase: str) -> List[float]:
        return list(self._times.get(phase, []))

    def total_ms(self, phase: str) -> float:
        return float(sum(self._times.get(phase, [])))

    def summary(self) -> str:
        lines = ["TrainingStats:"]
        for k in self.get_keys():
            v = self._times[k]
            lines.append(f"  {k}: n={len(v)} total={sum(v):.1f}ms "
                         f"mean={sum(v) / len(v):.2f}ms")
        for k in sorted(self._counters):
            lines.append(f"  {k}: count={self._counters[k]}")
        return "\n".join(lines)


class _PhaseTimer:
    def __init__(self, stats: TrainingStats, phase: str):
        self._stats = stats
        self._phase = phase

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._stats.add_time(self._phase,
                             (time.perf_counter() - self._t0) * 1e3)
        return False
