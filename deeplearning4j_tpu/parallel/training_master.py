"""TrainingMaster / TrainingWorker SPI + parameter-averaging master.

Reference: `dl4j-spark/.../spark/api/TrainingMaster.java`,
`TrainingWorker.java` (the pluggable distributed-training contract),
`spark/impl/paramavg/ParameterAveragingTrainingMaster.java:75`
(`executeTrainingDirect:356`, `doIteration:647`, `processResults:767` —
split the stream into averaging windows, fan out to workers, tree-reduce
parameter vectors, average, broadcast) and
`ParameterAveragingTrainingWorker.java:162`.

TPU-native redesign: the reference uses this tier because its only
intra-node sync primitive is full-parameter shipping over Spark TCP. On TPU
the PRIMARY data-parallel path is `ParallelWrapper` — one pjit-compiled step
whose gradient all-reduce rides ICI inside the XLA program. The
TrainingMaster SPI is kept as the seam for the *multi-pod / DCN* role the
Spark master played: coarse-grained parameter averaging between model
replicas that do NOT share a fast interconnect. Workers here run in-process
(the analogue of the reference's Spark `local[N]` test masters); a real
deployment points each worker at its own pod slice and the aggregate step at
a DCN collective or host-side reduce.
"""
from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.parallel.repartition import (
    Repartition,
    RepartitionStrategy,
    balanced_partitions,
    should_repartition,
)
from deeplearning4j_tpu.parallel.stats import TrainingStats

logger = logging.getLogger("deeplearning4j_tpu")


# ---------------------------------------------------------------------------
# SPI


@dataclass
class TrainingResult:
    """What a worker ships back (reference `ExecuteWorkerFlatMap` returns
    (params, updaterState, score) via `ParameterAveragingTrainingResult`)."""

    params: np.ndarray  # flat parameter vector
    updater_state: Optional[np.ndarray]  # flat updater-state vector
    score: float
    num_examples: int


class TrainingHook:
    """Observer invoked around every worker minibatch (reference
    `spark/api/TrainingHook.java`: onTrainingStart/End and
    preUpdate/postUpdate — the seam the reference's parameter-server Spark
    integration plugs into, `ParameterServerTrainingHook.java`). Hooks run
    on worker shard threads, outside the compiled step; one worker instance
    serves all shards (unlike the reference, where each Spark executor
    deserializes its own worker copy), so callback invocations are
    serialized under a worker-level lock — hook state sees a consistent
    interleaving without needing to be thread-safe."""

    def on_training_start(self, net) -> None:
        pass

    def on_training_end(self, net) -> None:
        pass

    def pre_update(self, ds: DataSet, net) -> None:
        pass

    def post_update(self, ds: DataSet, net) -> None:
        pass


class TrainingWorker:
    """Per-executor training contract (reference
    `spark/api/TrainingWorker.java`)."""

    def __init__(self):
        self.training_hooks: List[TrainingHook] = []
        self._hook_lock = threading.RLock()

    def add_hook(self, hook: TrainingHook) -> None:
        """Reference `TrainingWorker.addHook`."""
        with self._hook_lock:
            self.training_hooks.append(hook)

    def remove_hook(self, hook: TrainingHook) -> None:
        with self._hook_lock:
            self.training_hooks.remove(hook)

    def _run_hooks(self, method: str, *args) -> None:
        with self._hook_lock:
            hooks = list(self.training_hooks)
            # callbacks run under the lock for the documented serialization
            # guarantee, but over a snapshot so a hook may add/remove hooks
            # (the lock is reentrant) without corrupting this iteration
            for h in hooks:
                getattr(h, method)(*args)

    def get_initial_model(self):
        raise NotImplementedError

    def process_minibatch(self, ds: DataSet, net, is_last: bool) -> None:
        raise NotImplementedError

    def get_final_result(self, net) -> TrainingResult:
        raise NotImplementedError


class TrainingMaster:
    """Distributed-training contract (reference
    `spark/api/TrainingMaster.java`): how to partition work, run workers,
    and combine results."""

    def execute_training(self, net, iterator: DataSetIterator) -> None:
        raise NotImplementedError

    def get_training_stats(self) -> Optional[TrainingStats]:
        return None


# ---------------------------------------------------------------------------
# parameter averaging


def _flat_updater_state(net) -> Optional[np.ndarray]:
    from jax.flatten_util import ravel_pytree

    upd = net.get_updater_state()
    flat, _ = ravel_pytree(upd)
    return np.asarray(flat) if flat.size else None


def _set_updater_state_flat(net, flat: np.ndarray) -> None:
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    _, unravel = ravel_pytree(net.get_updater_state())
    net._upd_state = unravel(jnp.asarray(flat))


class ParameterAveragingTrainingWorker(TrainingWorker):
    """Reference `ParameterAveragingTrainingWorker.java:162`
    (`processMinibatch` = net.fit(ds))."""

    def __init__(self, template_net):
        super().__init__()
        self._template = template_net

    def get_initial_model(self):
        net = self._template.clone()
        self._run_hooks("on_training_start", net)
        return net

    def process_minibatch(self, ds: DataSet, net, is_last: bool) -> None:
        self._run_hooks("pre_update", ds, net)
        net.fit(ds)
        self._run_hooks("post_update", ds, net)
        if is_last:
            self._run_hooks("on_training_end", net)

    def get_final_result(self, net) -> TrainingResult:
        return TrainingResult(params=net.params(),
                              updater_state=_flat_updater_state(net),
                              score=net.score_value or float("nan"),
                              num_examples=0)


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Synchronous coarse-grained data parallelism by parameter averaging
    (reference `ParameterAveragingTrainingMaster.java:75`).

    Stream is consumed in *averaging windows* of
    `num_workers × averaging_frequency` minibatches; each worker fits
    `averaging_frequency` of them on its own replica, then parameter vectors
    (and optionally updater state) are averaged and re-broadcast — the same
    schedule as the reference's `doIteration:647` → `processResults:767`
    (`results.aggregate(Add/Combine):772` → `params.divi(aggCount):783`).
    """

    def __init__(self, num_workers: int, averaging_frequency: int = 5,
                 average_updaters: bool = True,
                 collect_training_stats: bool = False,
                 worker: Optional[TrainingWorker] = None,
                 repartition: Repartition = Repartition.ALWAYS,
                 repartition_strategy: RepartitionStrategy = RepartitionStrategy.ROUND_ROBIN,
                 rng_seed: Optional[int] = None):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        self.num_workers = num_workers
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self.repartition = repartition
        self.repartition_strategy = repartition_strategy
        self._rng_seed = rng_seed
        self._worker_factory = worker
        self._stats = TrainingStats() if collect_training_stats else None

    # -- SPI ---------------------------------------------------------------
    def get_training_stats(self) -> Optional[TrainingStats]:
        return self._stats

    def execute_training_paths(self, net, paths) -> None:
        """Train from EXPORTED dataset shards (files written by
        `parallel/export.batch_and_export`) — the reference's second RDD
        training approach (`RDDTrainingApproach.Export`,
        `executeTrainingPathsHelper:506`): workers stream batches from
        paths one file at a time, so the training set never has to fit in
        memory. Same averaging schedule as `execute_training`."""
        from deeplearning4j_tpu.datasets.iterators import FileDataSetIterator

        self.execute_training(net, FileDataSetIterator(paths))

    def execute_training(self, net, iterator: DataSetIterator) -> None:
        net._ensure_init()
        worker = self._worker_factory or ParameterAveragingTrainingWorker(net)
        window = self.num_workers * self.averaging_frequency
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        try:
            batches: List[DataSet] = []
            for ds in iterator:
                batches.append(ds)
                if len(batches) == window:
                    self._do_iteration(net, worker, batches, pool)
                    batches = []
            if batches:  # tail window (reference tolerates short splits)
                self._do_iteration(net, worker, batches, pool)
        finally:
            pool.shutdown(wait=True)

    # -- internals ---------------------------------------------------------
    def _do_iteration(self, net, worker: TrainingWorker,
                      batches: Sequence[DataSet],
                      pool: ThreadPoolExecutor) -> None:
        """One averaging window (reference `doIteration:647`)."""
        stats = self._stats
        # split: round-robin batches over workers (reference
        # balancedRandomSplit + repartition)
        if stats:
            t = stats.timer("split")
            t.__enter__()
        if should_repartition(len(batches), self.num_workers, self.repartition):
            shards = balanced_partitions(batches, self.num_workers,
                                         self.repartition_strategy,
                                         seed=self._rng_seed)
        else:  # keep arrival-order contiguous chunks (no data movement)
            shards = balanced_partitions(batches, self.num_workers,
                                         RepartitionStrategy.BALANCED,
                                         seed=0)
        if stats:
            t.__exit__()

        def run_worker(shard: List[DataSet]) -> TrainingResult:
            wnet = worker.get_initial_model()
            n = 0
            for j, ds in enumerate(shard):
                worker.process_minibatch(ds, wnet, j == len(shard) - 1)
                n += ds.num_examples()
            result = worker.get_final_result(wnet)
            result.num_examples = n
            return result

        if stats:
            t = stats.timer("fit")
            t.__enter__()
        results = list(pool.map(run_worker, shards))
        if stats:
            t.__exit__()

        with (stats.timer("aggregate") if stats else _nullcontext()):
            # plain average (reference `processResults:767-783`: aggregate
            # add + divi by count, NOT example-weighted)
            params = np.mean([r.params for r in results], axis=0)
            upd = None
            if self.average_updaters:
                vs = [r.updater_state for r in results]
                if all(v is not None for v in vs) and vs:
                    upd = np.mean(vs, axis=0)

        with (stats.timer("broadcast") if stats else _nullcontext()):
            net.set_params(params)
            if upd is not None:
                _set_updater_state_flat(net, upd)
        net.score_value = float(np.mean([r.score for r in results]))
        # master clock advances by the longest worker shard (= the number of
        # sequential optimizer steps this window represents)
        net.iteration += -(-len(batches) // self.num_workers)
        for listener in getattr(net, "listeners", []):
            listener.iteration_done(net, net.iteration)


class _nullcontext:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# ---------------------------------------------------------------------------
# user-facing wrappers (reference SparkDl4jMultiLayer / SparkComputationGraph)


class DistributedMultiLayer:
    """User-facing handle pairing a network with a TrainingMaster (reference
    `spark/impl/multilayer/SparkDl4jMultiLayer.java` — `fit(RDD):216` →
    `trainingMaster.executeTraining:220`).

    evaluate / calculate_score / score_examples genuinely DISTRIBUTE (r5):
    batches shard round-robin over the master's worker pool, each worker
    evaluates its shard on its own replica (the reference broadcasts the
    net to executors the same way), and per-worker results merge —
    `Evaluation.merge` for evaluate (reference
    `SparkDl4jMultiLayer.evaluate:511-528` → `IEvaluation.merge`),
    example-weighted score sums for calculate_score (`calculateScore:382`),
    order-restoring concatenation for score_examples
    (`scoreExamples:382-416`)."""

    def __init__(self, net, training_master: TrainingMaster):
        self.net = net
        self.training_master = training_master

    def _num_workers(self) -> int:
        return getattr(self.training_master, "num_workers", 4)

    def fit(self, data, epochs: int = 1):
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        for _ in range(epochs):
            data.reset()
            self.training_master.execute_training(self.net, data)
            self.net.epoch += 1
        return self.net

    # -- distributed inference-side operations -----------------------------
    def _shard_map(self, data, per_batch_fn):
        """Round-robin the iterator's batches over worker threads, each
        holding its own replica; returns [(batch_index, result)] in
        arbitrary completion order. The replica clone mirrors the
        reference's per-executor deserialized network copy."""
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        if isinstance(data, (DataSet, MultiDataSet)):
            data = ListDataSetIterator([data])
        batches = list(data)
        n_workers = max(1, min(self._num_workers(), len(batches) or 1))
        if n_workers == 1:
            # single shard: evaluate on the net itself — no clone, no pool
            # (score(ds) and per-epoch calculator loops stay cheap)
            return [(idx, per_batch_fn(self.net, ds))
                    for idx, ds in enumerate(batches)]
        shards = [[] for _ in range(n_workers)]
        for idx, ds in enumerate(batches):
            shards[idx % n_workers].append((idx, ds))

        def run_shard(shard):
            if not shard:
                return []
            replica = self.net.clone()
            return [(idx, per_batch_fn(replica, ds)) for idx, ds in shard]

        with ThreadPoolExecutor(max_workers=n_workers) as pool:
            out = []
            for part in pool.map(run_shard, shards):
                out.extend(part)
        return out

    def evaluate(self, data, labels: Optional[List[str]] = None,
                 top_n: int = 1):
        """Cluster-style evaluation: per-shard `Evaluation`s merged into
        one (reference `SparkDl4jMultiLayer.evaluate:511-528`). Confusion
        counts are additive, so the merged result equals single-device
        `net.evaluate` on the same data exactly."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        def eval_batch(replica, ds):
            # the replica's own evaluate handles container specifics
            # (MultiDataSet inputs, masks) for both MLN and CG
            return replica.evaluate(ds, labels=labels, top_n=top_n)

        merged = Evaluation(labels=labels, top_n=top_n)
        for _, ev in sorted(self._shard_map(data, eval_batch)):
            merged.merge(ev)
        return merged

    def calculate_score(self, data, average: bool = True) -> float:
        """Loss over the full dataset, computed shard-parallel and combined
        example-weighted (reference `SparkDl4jMultiLayer.calculateScore:382`
        — sum of per-partition scores, optionally / total examples)."""
        results = self._shard_map(
            data, lambda replica, ds: (replica.score(ds) * ds.num_examples(),
                                       ds.num_examples()))
        total = sum(s for _, (s, _) in results)
        n = sum(n for _, (_, n) in results)
        return float(total / n) if average and n else float(total)

    def score(self, ds) -> float:
        """Mean loss on one batch (reference `SparkDl4jMultiLayer.score`)."""
        return self.calculate_score(ds, average=True)

    def score_examples(self, data,
                       add_regularization: bool = False) -> np.ndarray:
        """Per-example scores over the dataset, shard-parallel, returned in
        the ORIGINAL example order (reference
        `SparkDl4jMultiLayer.scoreExamples:382-416`)."""
        results = self._shard_map(
            data,
            lambda replica, ds: replica.score_examples(
                ds, add_regularization=add_regularization))
        return np.concatenate([r for _, r in sorted(results)]) \
            if results else np.zeros((0,))

    def get_network(self):
        return self.net


class DistributedComputationGraph(DistributedMultiLayer):
    """Reference `spark/impl/graph/SparkComputationGraph.java`."""
