"""TrainingMaster / TrainingWorker SPI + parameter-averaging master.

Reference: `dl4j-spark/.../spark/api/TrainingMaster.java`,
`TrainingWorker.java` (the pluggable distributed-training contract),
`spark/impl/paramavg/ParameterAveragingTrainingMaster.java:75`
(`executeTrainingDirect:356`, `doIteration:647`, `processResults:767` —
split the stream into averaging windows, fan out to workers, tree-reduce
parameter vectors, average, broadcast) and
`ParameterAveragingTrainingWorker.java:162`.

TPU-native redesign: the reference uses this tier because its only
intra-node sync primitive is full-parameter shipping over Spark TCP. On TPU
the PRIMARY data-parallel path is `ParallelWrapper` — one pjit-compiled step
whose gradient all-reduce rides ICI inside the XLA program. The
TrainingMaster SPI is kept as the seam for the *multi-pod / DCN* role the
Spark master played: coarse-grained parameter averaging between model
replicas that do NOT share a fast interconnect. Workers here run in-process
(the analogue of the reference's Spark `local[N]` test masters); a real
deployment points each worker at its own pod slice and the aggregate step at
a DCN collective or host-side reduce.
"""
from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import (
    FIRST_COMPLETED,
    Future,
    ThreadPoolExecutor,
    wait as _futures_wait,
)
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.parallel.repartition import (
    Repartition,
    RepartitionStrategy,
    balanced_partitions,
    should_repartition,
)
from deeplearning4j_tpu.parallel.stats import TrainingStats
from deeplearning4j_tpu.parallel.time_source import (
    TimeSource,
    TimeSourceProvider,
)

logger = logging.getLogger("deeplearning4j_tpu")


# ---------------------------------------------------------------------------
# worker health / elasticity


class NoHealthyWorkersError(RuntimeError):
    """Every worker in the pool has been dropped — training cannot proceed.
    Raised instead of hanging on an averaging barrier no one will reach."""


class WorkerFailureError(RuntimeError):
    """A shard kept failing across re-dispatches (bounded attempts
    exhausted); the last worker exception is chained as __cause__."""


class NonFiniteWorkerResultError(RuntimeError):
    """A worker shipped back non-finite parameters or updater state — a
    replica that diverged (NaN gradient, poisoned shard data). The result
    is quarantined (it never reaches the average: one NaN replica would
    poison every parameter of the merged model) and the shard is treated
    exactly like a failed shard: re-dispatched to a surviving worker
    under the usual retry/backoff/drop discipline."""


class _WindowAbort(Exception):
    """Internal: a worker was dropped mid-window. Nothing has been committed
    to the master net yet, so the window repartitions over the surviving
    workers and re-runs from the same master parameters."""


class _ShardAbandoned(Exception):
    """Internal: raised inside an orphaned shard thread (its result was
    discarded after a timeout/abort) so it stops training dead weight,
    frees its pool slot, and stops stamping heartbeats for a worker the
    master no longer trusts."""


@dataclass
class WorkerHealth:
    """Per-worker liveness record the master keeps across averaging windows
    and epochs (the elastic layer's analogue of Spark's executor liveness
    view, which the reference delegates to the cluster manager)."""

    worker_id: int
    alive: bool = True
    consecutive_failures: int = 0
    total_failures: int = 0
    shards_completed: int = 0
    last_heartbeat_ms: Optional[int] = None
    last_error: Optional[str] = None


@dataclass
class _ShardTask:
    """One shard's dispatch bookkeeping inside a window."""

    index: int
    shard: List[DataSet]
    health: WorkerHealth
    attempts: int = 0
    queued_at: float = 0.0              # monotonic; set at submit
    started_at: Optional[float] = None  # monotonic; set by the pool thread
    not_before: float = 0.0             # monotonic; backoff gate for retry
    abandoned: bool = False             # result discarded; thread bails out

    def deadline(self, timeout: float) -> float:
        """Expiry instant: from actual start when the pool thread picked
        the task up, else from submit — a task that cannot even START
        within the timeout is starved by hung threads saturating the
        pool, and must count as a failure of its assigned worker (the
        drop path then converges to NoHealthyWorkersError instead of
        spinning forever waiting for a slot that will never free)."""
        return (self.started_at if self.started_at is not None
                else self.queued_at) + timeout


_worker_ctx = threading.local()


def current_worker_id() -> Optional[int]:
    """Worker id of the calling shard thread, or None outside a worker.
    The seam distributed fault injectors key on (see
    `parallel/fault_tolerance.WorkerCrashInjector`)."""
    return getattr(_worker_ctx, "worker_id", None)


# ---------------------------------------------------------------------------
# SPI


@dataclass
class TrainingResult:
    """What a worker ships back (reference `ExecuteWorkerFlatMap` returns
    (params, updaterState, score) via `ParameterAveragingTrainingResult`)."""

    params: np.ndarray  # flat parameter vector
    updater_state: Optional[np.ndarray]  # flat updater-state vector
    score: float
    num_examples: int


class TrainingHook:
    """Observer invoked around every worker minibatch (reference
    `spark/api/TrainingHook.java`: onTrainingStart/End and
    preUpdate/postUpdate — the seam the reference's parameter-server Spark
    integration plugs into, `ParameterServerTrainingHook.java`). Hooks run
    on worker shard threads, outside the compiled step; one worker instance
    serves all shards (unlike the reference, where each Spark executor
    deserializes its own worker copy). The hook LIST is snapshotted under a
    lock, but callbacks themselves run unlocked and may fire concurrently
    from different shard threads — a hook that blocks (e.g. a straggler
    injector sleeping) must not stall the other workers, so stateful hooks
    guard their own mutable state. `current_worker_id()` identifies the
    calling shard thread."""

    def on_training_start(self, net) -> None:
        pass

    def on_training_end(self, net) -> None:
        pass

    def pre_update(self, ds: DataSet, net) -> None:
        pass

    def post_update(self, ds: DataSet, net) -> None:
        pass


class TrainingWorker:
    """Per-executor training contract (reference
    `spark/api/TrainingWorker.java`)."""

    def __init__(self):
        self.training_hooks: List[TrainingHook] = []
        self._hook_lock = threading.RLock()

    def add_hook(self, hook: TrainingHook) -> None:
        """Reference `TrainingWorker.addHook`."""
        with self._hook_lock:
            self.training_hooks.append(hook)

    def remove_hook(self, hook: TrainingHook) -> None:
        with self._hook_lock:
            self.training_hooks.remove(hook)

    def _run_hooks(self, method: str, *args) -> None:
        with self._hook_lock:
            # snapshot under the lock (a hook may add/remove hooks), but
            # invoke OUTSIDE it: a blocking hook on one shard thread — a
            # SlowWorkerInjector, a network-backed PS hook mid-retry — must
            # not freeze every other worker's minibatch callbacks
            hooks = list(self.training_hooks)
        for h in hooks:
            getattr(h, method)(*args)

    def get_initial_model(self):
        raise NotImplementedError

    def process_minibatch(self, ds: DataSet, net, is_last: bool) -> None:
        raise NotImplementedError

    def get_final_result(self, net) -> TrainingResult:
        raise NotImplementedError


class TrainingMaster:
    """Distributed-training contract (reference
    `spark/api/TrainingMaster.java`): how to partition work, run workers,
    and combine results."""

    def execute_training(self, net, iterator: DataSetIterator) -> None:
        raise NotImplementedError

    def get_training_stats(self) -> Optional[TrainingStats]:
        return None


# ---------------------------------------------------------------------------
# parameter averaging


def _flat_updater_state(net) -> Optional[np.ndarray]:
    from jax.flatten_util import ravel_pytree

    upd = net.get_updater_state()
    flat, _ = ravel_pytree(upd)
    return np.asarray(flat) if flat.size else None


def _set_updater_state_flat(net, flat: np.ndarray) -> None:
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    _, unravel = ravel_pytree(net.get_updater_state())
    net._upd_state = unravel(jnp.asarray(flat))


class ParameterAveragingTrainingWorker(TrainingWorker):
    """Reference `ParameterAveragingTrainingWorker.java:162`
    (`processMinibatch` = net.fit(ds))."""

    def __init__(self, template_net):
        super().__init__()
        self._template = template_net

    def get_initial_model(self):
        net = self._template.clone()
        self._run_hooks("on_training_start", net)
        return net

    def process_minibatch(self, ds: DataSet, net, is_last: bool) -> None:
        self._run_hooks("pre_update", ds, net)
        net.fit(ds)
        self._run_hooks("post_update", ds, net)
        if is_last:
            self._run_hooks("on_training_end", net)

    def get_final_result(self, net) -> TrainingResult:
        return TrainingResult(params=net.params(),
                              updater_state=_flat_updater_state(net),
                              score=net.score_value or float("nan"),
                              num_examples=0)


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Synchronous coarse-grained data parallelism by parameter averaging
    (reference `ParameterAveragingTrainingMaster.java:75`).

    Stream is consumed in *averaging windows* of
    `num_workers × averaging_frequency` minibatches; each worker fits
    `averaging_frequency` of them on its own replica, then parameter vectors
    (and optionally updater state) are averaged and re-broadcast — the same
    schedule as the reference's `doIteration:647` → `processResults:767`
    (`results.aggregate(Add/Combine):772` → `params.divi(aggCount):783`).

    Elasticity (no reference analogue — Spark owned task retry there):

    - Every shard dispatch is watched: a worker that raises, or exceeds
      `worker_timeout` seconds on one shard, is marked failed and its shard
      is re-dispatched to a surviving worker after exponential backoff
      (`retry_backoff × backoff_multiplier^attempt`), with per-shard
      attempts bounded by `max_retries` re-dispatches. Set
      `worker_timeout` comfortably ABOVE the first-step jit-compile
      latency: the first window pays compilation per replica, and a
      too-tight timeout reads that as a straggler — training still
      completes (degradation is graceful), but with needlessly shed
      capacity.
    - A worker shipping back NON-FINITE parameters or updater state (a
      diverged replica: NaN gradient, poisoned shard data) is treated
      exactly like a crashed worker — the result is quarantined, never
      averaged in (one NaN replica would poison every merged parameter),
      and the shard re-dispatches (`NonFiniteWorkerResultError`, counted
      as `nonfinite_results` in `TrainingStats`).
    - A worker accumulating more than `max_retries` CONSECUTIVE failures is
      dropped from the pool; the in-flight window aborts (nothing was
      committed) and re-runs repartitioned over the survivors, so a
      degraded pool trains exactly like a master configured with the
      smaller worker count. An empty pool raises `NoHealthyWorkersError`.
    - Aggregation weights each worker result by its example count
      (`example_weighted=True`, the default) so uneven shards — tail
      windows, degraded pools — average correctly; equal shards reduce to
      the reference's plain `divi(aggCount)` mean. Pass False for the
      reference's unweighted behavior.
    - Per-worker `WorkerHealth` records (heartbeat stamped per minibatch
      from the configured `TimeSource`) persist across windows and epochs;
      failures/retries/drops also count into `TrainingStats` when
      `collect_training_stats=True`.
    """

    def __init__(self, num_workers: int, averaging_frequency: int = 5,
                 average_updaters: bool = True,
                 collect_training_stats: bool = False,
                 worker: Optional[TrainingWorker] = None,
                 repartition: Repartition = Repartition.ALWAYS,
                 repartition_strategy: RepartitionStrategy = RepartitionStrategy.ROUND_ROBIN,
                 rng_seed: Optional[int] = None,
                 worker_timeout: Optional[float] = None,
                 max_retries: int = 2,
                 retry_backoff: float = 0.05,
                 backoff_multiplier: float = 2.0,
                 example_weighted: bool = True,
                 time_source: Optional[TimeSource] = None):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        if worker_timeout is not None and worker_timeout <= 0:
            raise ValueError("worker_timeout must be positive (or None)")
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.num_workers = num_workers
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self.repartition = repartition
        self.repartition_strategy = repartition_strategy
        self.worker_timeout = worker_timeout
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self.backoff_multiplier = backoff_multiplier
        self.example_weighted = example_weighted
        self._rng_seed = rng_seed
        self._worker_factory = worker
        self._stats = TrainingStats() if collect_training_stats else None
        self._time_source = time_source or TimeSourceProvider.get_instance()
        self.worker_health: List[WorkerHealth] = [
            WorkerHealth(i) for i in range(num_workers)]

    # -- SPI ---------------------------------------------------------------
    def get_training_stats(self) -> Optional[TrainingStats]:
        return self._stats

    # -- health ------------------------------------------------------------
    def alive_workers(self) -> List[WorkerHealth]:
        return [h for h in self.worker_health if h.alive]

    def reset_worker_health(self) -> None:
        """Re-admit every worker (e.g. after replacing failed hardware)."""
        self.worker_health = [WorkerHealth(i)
                              for i in range(self.num_workers)]

    def worker_heartbeat_age_ms(self, worker_id: int) -> Optional[int]:
        """Milliseconds since `worker_id` last finished a minibatch, or
        None if it never heartbeat."""
        hb = self.worker_health[worker_id].last_heartbeat_ms
        if hb is None:
            return None
        return self._time_source.current_time_millis() - hb

    def _heartbeat(self, worker_id: int) -> None:
        self.worker_health[worker_id].last_heartbeat_ms = (
            self._time_source.current_time_millis())

    def execute_training_paths(self, net, paths) -> None:
        """Train from EXPORTED dataset shards (files written by
        `parallel/export.batch_and_export`) — the reference's second RDD
        training approach (`RDDTrainingApproach.Export`,
        `executeTrainingPathsHelper:506`): workers stream batches from
        paths one file at a time, so the training set never has to fit in
        memory. Same averaging schedule as `execute_training`."""
        from deeplearning4j_tpu.datasets.iterators import FileDataSetIterator

        self.execute_training(net, FileDataSetIterator(paths))

    def execute_training(self, net, iterator: DataSetIterator) -> None:
        net._ensure_init()
        worker = self._worker_factory or ParameterAveragingTrainingWorker(net)
        window = self.num_workers * self.averaging_frequency
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        try:
            batches: List[DataSet] = []
            for ds in iterator:
                batches.append(ds)
                if len(batches) == window:
                    self._do_iteration(net, worker, batches, pool)
                    batches = []
            if batches:  # tail window (reference tolerates short splits)
                self._do_iteration(net, worker, batches, pool)
        finally:
            # don't block on a hung straggler thread: shards that matter
            # were already awaited (with timeout) inside the window loop
            pool.shutdown(wait=False)

    # -- internals ---------------------------------------------------------
    def _partition(self, batches: Sequence[DataSet],
                   n_workers: int) -> List[List[DataSet]]:
        """Split one window's batches over `n_workers` (reference
        balancedRandomSplit + repartition)."""
        if should_repartition(len(batches), n_workers, self.repartition):
            return balanced_partitions(batches, n_workers,
                                       self.repartition_strategy,
                                       seed=self._rng_seed)
        # keep arrival-order contiguous chunks (no data movement)
        return balanced_partitions(batches, n_workers,
                                   RepartitionStrategy.BALANCED, seed=0)

    def _do_iteration(self, net, worker: TrainingWorker,
                      batches: Sequence[DataSet],
                      pool: ThreadPoolExecutor) -> None:
        """One averaging window (reference `doIteration:647`), re-run over
        the surviving pool whenever a worker is dropped mid-window."""
        stats = self._stats
        while True:
            alive = self.alive_workers()
            if not alive:
                raise NoHealthyWorkersError(
                    f"all {self.num_workers} workers have been dropped "
                    f"(per-worker failures: "
                    f"{[h.total_failures for h in self.worker_health]}) — "
                    "no healthy worker left to train on")
            with (stats.timer("split") if stats else _nullcontext()):
                shards = self._partition(batches, len(alive))
            try:
                with (stats.timer("fit") if stats else _nullcontext()):
                    results = self._run_window(worker, shards, alive, pool)
                break
            except _WindowAbort:
                if stats:
                    stats.increment("window_reruns")
                logger.warning(
                    "averaging window aborted (worker dropped); re-running "
                    "over %d surviving workers", len(self.alive_workers()))

        with (stats.timer("aggregate") if stats else _nullcontext()):
            # example-weighted average so uneven shards (tail windows,
            # degraded pools) combine correctly; with equal shard sizes this
            # IS the reference's plain mean (`processResults:767-783`:
            # aggregate add + divi by count), which `example_weighted=False`
            # restores exactly
            weights = None
            if self.example_weighted:
                w = np.asarray([r.num_examples for r in results], np.float64)
                if w.sum() > 0:
                    weights = w
            params = np.average([r.params for r in results], axis=0,
                                weights=weights)
            upd = None
            if self.average_updaters:
                vs = [r.updater_state for r in results]
                if all(v is not None for v in vs) and vs:
                    upd = np.average(vs, axis=0, weights=weights)

        with (stats.timer("broadcast") if stats else _nullcontext()):
            net.set_params(params)
            if upd is not None:
                _set_updater_state_flat(net, upd)
        net.score_value = float(np.average([r.score for r in results],
                                           weights=weights))
        # master clock advances by the longest worker shard (= the number of
        # sequential optimizer steps this window represents)
        net.iteration += max(len(s) for s in shards)
        for listener in getattr(net, "listeners", []):
            listener.iteration_done(net, net.iteration)

    # -- elastic window execution ------------------------------------------
    def _run_shard(self, worker: TrainingWorker,
                   task: _ShardTask) -> TrainingResult:
        task.started_at = time.monotonic()
        wid = task.health.worker_id
        _worker_ctx.worker_id = wid
        self._heartbeat(wid)
        try:
            wnet = worker.get_initial_model()
            n = 0
            for j, ds in enumerate(task.shard):
                if task.abandoned:
                    # orphaned (timed out / window aborted): stop training
                    # dead weight, free the slot, and above all stop
                    # stamping heartbeats the master would misread as the
                    # dropped worker being healthy
                    raise _ShardAbandoned(f"shard {task.index}")
                worker.process_minibatch(ds, wnet, j == len(task.shard) - 1)
                n += ds.num_examples()
                if not task.abandoned:
                    self._heartbeat(wid)
            result = worker.get_final_result(wnet)
            result.num_examples = n
            self._check_result_finite(result, wid, task.index)
            return result
        finally:
            _worker_ctx.worker_id = None

    @staticmethod
    def _check_result_finite(result: TrainingResult, worker_id: int,
                             shard_index: int) -> None:
        """Quarantine gate on the averaging input: a worker returning
        non-finite params/updater state is a FAILED shard (raises
        `NonFiniteWorkerResultError` → retry/backoff/drop machinery),
        never averaged in. The score is deliberately not checked — a
        worker that never scored reports NaN score with finite params,
        and the average ignores it."""
        bad = None
        if not np.all(np.isfinite(result.params)):
            bad = "parameters"
        elif result.updater_state is not None \
                and not np.all(np.isfinite(result.updater_state)):
            bad = "updater state"
        if bad is not None:
            logger.warning(
                "quarantining non-finite result from worker %d (shard "
                "%d): %s contain NaN/Inf — never averaged in;"
                " re-dispatching", worker_id, shard_index, bad)
            raise NonFiniteWorkerResultError(
                f"worker {worker_id} returned non-finite {bad} for shard "
                f"{shard_index} — result quarantined, shard re-dispatched")

    def _run_window(self, worker: TrainingWorker,
                    shards: List[List[DataSet]],
                    alive: List[WorkerHealth],
                    pool: ThreadPoolExecutor) -> List[TrainingResult]:
        """Dispatch shards, await with per-shard timeout, retry/re-dispatch
        failures, drop repeat offenders (raising `_WindowAbort`)."""
        results: List[Optional[TrainingResult]] = [None] * len(shards)
        inflight: Dict[Future, _ShardTask] = {}
        pending: List[_ShardTask] = []  # retries gated by backoff not_before
        for health, (i, shard) in zip(alive, enumerate(shards)):
            task = _ShardTask(i, shard, health, queued_at=time.monotonic())
            inflight[pool.submit(self._run_shard, worker, task)] = task
        try:
            self._watch_window(worker, pool, results, inflight, pending)
        except Exception:
            # window abort / give-up: whatever is still running is dead
            # weight — tell those threads to bail out and stop heartbeating
            for t in inflight.values():
                t.abandoned = True
            raise
        return results  # type: ignore[return-value]  # all slots filled

    def _watch_window(self, worker: TrainingWorker,
                      pool: ThreadPoolExecutor,
                      results: List[Optional[TrainingResult]],
                      inflight: Dict[Future, _ShardTask],
                      pending: List[_ShardTask]) -> None:
        while inflight or pending:
            now = time.monotonic()
            for task in [t for t in pending if now >= t.not_before]:
                pending.remove(task)
                task.started_at = None
                task.queued_at = time.monotonic()
                inflight[pool.submit(self._run_shard, worker, task)] = task
            if not inflight:  # only backoff-gated retries remain
                time.sleep(max(0.0, min(t.not_before for t in pending) - now))
                continue
            done, _ = _futures_wait(
                set(inflight),
                timeout=self._wait_timeout(inflight, pending),
                return_when=FIRST_COMPLETED)
            now = time.monotonic()
            expired: List[Future] = []
            if not done and self.worker_timeout is not None:
                # a future that completed between the wait and this check
                # is NOT expired — its (successful) result is harvested on
                # the next loop pass instead of being discarded and
                # charged to the worker as a phantom failure
                expired = [f for f, t in inflight.items()
                           if now >= t.deadline(self.worker_timeout)
                           and not f.done()]
                if not expired:
                    continue
            for f in done:
                task = inflight.pop(f)
                exc = f.exception()
                if exc is None:
                    results[task.index] = f.result()
                    task.health.consecutive_failures = 0
                    task.health.shards_completed += 1
                else:
                    self._on_shard_failure(task, exc, timed_out=False)
                    self._schedule_retry(task, pending, exc)
            for f in expired:
                task = inflight.pop(f)
                f.cancel()  # a queued task is cancelled outright; a running
                task.abandoned = True  # thread bails at its next minibatch
                exc = TimeoutError(
                    f"worker {task.health.worker_id} exceeded "
                    f"worker_timeout={self.worker_timeout}s on shard "
                    f"{task.index}")
                self._on_shard_failure(task, exc, timed_out=True)
                self._schedule_retry(task, pending, exc)

    def _wait_timeout(self, inflight: Dict[Future, _ShardTask],
                      pending: List[_ShardTask]) -> Optional[float]:
        now = time.monotonic()
        wakeups = [t.not_before for t in pending]
        if self.worker_timeout is not None:
            wakeups += [t.deadline(self.worker_timeout)
                        for t in inflight.values()]
        if not wakeups:
            return None
        return max(0.0, min(wakeups) - now)

    def _on_shard_failure(self, task: _ShardTask, exc: BaseException,
                          timed_out: bool) -> None:
        h = task.health
        task.attempts += 1
        h.consecutive_failures += 1
        h.total_failures += 1
        h.last_error = f"{type(exc).__name__}: {exc}"
        if self._stats:
            self._stats.increment("worker_failures")
            if timed_out:
                self._stats.increment("worker_timeouts")
            if isinstance(exc, NonFiniteWorkerResultError):
                self._stats.increment("nonfinite_results")
        logger.warning(
            "worker %d %s on shard %d (shard attempt %d, consecutive "
            "worker failures %d/%d): %s",
            h.worker_id, "timed out" if timed_out else "failed", task.index,
            task.attempts, h.consecutive_failures, self.max_retries + 1,
            h.last_error)
        if h.consecutive_failures > self.max_retries:
            h.alive = False
            if self._stats:
                self._stats.increment("workers_dropped")
            logger.warning(
                "worker %d dropped after %d consecutive failures; pool "
                "shrinks to %d healthy workers",
                h.worker_id, h.consecutive_failures,
                len(self.alive_workers()))
            raise _WindowAbort(task.index)

    def _schedule_retry(self, task: _ShardTask, pending: List[_ShardTask],
                        exc: BaseException) -> None:
        """Queue the failed shard for re-dispatch to a surviving worker
        once its exponential backoff elapses. The backoff is a not-before
        gate consumed by the watch loop, NOT a sleep here — sleeping
        would stall harvesting/timeout detection for every other
        in-flight shard."""
        if task.attempts > self.max_retries:
            raise WorkerFailureError(
                f"shard {task.index} failed {task.attempts} times across "
                f"re-dispatches (max_retries={self.max_retries}); last "
                f"error: {type(exc).__name__}: {exc}") from exc
        alive = self.alive_workers()
        if not alive:
            raise NoHealthyWorkersError(
                "no healthy worker left to re-dispatch shard "
                f"{task.index} to") from exc
        # prefer a DIFFERENT surviving worker; fall back to the same one
        # when it is the only survivor
        candidates = [h for h in alive if h is not task.health] or alive
        target = candidates[(task.attempts - 1) % len(candidates)]
        delay = self.retry_backoff * (self.backoff_multiplier
                                      ** (task.attempts - 1))
        if self._stats:
            self._stats.increment("worker_retries")
        logger.warning(
            "re-dispatching shard %d to worker %d after %.3fs backoff "
            "(attempt %d/%d)", task.index, target.worker_id, delay,
            task.attempts + 1, self.max_retries + 1)
        # a FRESH task object: the old one may still be held by an orphaned
        # thread whose bail-out check must not observe the retry's state
        pending.append(_ShardTask(task.index, task.shard, target,
                                  attempts=task.attempts,
                                  not_before=time.monotonic() + delay))


class _nullcontext:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# ---------------------------------------------------------------------------
# user-facing wrappers (reference SparkDl4jMultiLayer / SparkComputationGraph)


class DistributedMultiLayer:
    """User-facing handle pairing a network with a TrainingMaster (reference
    `spark/impl/multilayer/SparkDl4jMultiLayer.java` — `fit(RDD):216` →
    `trainingMaster.executeTraining:220`).

    evaluate / calculate_score / score_examples genuinely DISTRIBUTE (r5):
    batches shard round-robin over the master's worker pool, each worker
    evaluates its shard on its own replica (the reference broadcasts the
    net to executors the same way), and per-worker results merge —
    `Evaluation.merge` for evaluate (reference
    `SparkDl4jMultiLayer.evaluate:511-528` → `IEvaluation.merge`),
    example-weighted score sums for calculate_score (`calculateScore:382`),
    order-restoring concatenation for score_examples
    (`scoreExamples:382-416`)."""

    def __init__(self, net, training_master: TrainingMaster):
        self.net = net
        self.training_master = training_master
        # distributed-evaluate replica cache: clones (and, through them,
        # their jitted eval executables) persist across _shard_map
        # calls; invalidated by pointing the replicas at the net's
        # CURRENT params when they changed (see _replicas_for)
        self._replica_cache: list = []
        self._replica_params_ref = None

    def _num_workers(self) -> int:
        return getattr(self.training_master, "num_workers", 4)

    def fit(self, data, epochs: int = 1):
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        for _ in range(epochs):
            data.reset()
            self.training_master.execute_training(self.net, data)
            self.net.epoch += 1
        return self.net

    # -- distributed inference-side operations -----------------------------
    def _shard_map(self, data, per_batch_fn):
        """Round-robin the iterator's batches over worker threads, each
        holding its own replica; returns [(batch_index, result)] in
        arbitrary completion order. The replica clone mirrors the
        reference's per-executor deserialized network copy."""
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        if isinstance(data, (DataSet, MultiDataSet)):
            data = ListDataSetIterator([data])
        batches = list(data)
        n_workers = max(1, min(self._num_workers(), len(batches) or 1))
        if n_workers == 1:
            # single shard: evaluate on the net itself — no clone, no pool
            # (score(ds) and per-epoch calculator loops stay cheap)
            return [(idx, per_batch_fn(self.net, ds))
                    for idx, ds in enumerate(batches)]
        replicas = self._replicas_for(n_workers)
        shards = [[] for _ in range(n_workers)]
        for idx, ds in enumerate(batches):
            shards[idx % n_workers].append((idx, ds))

        def run_shard(wi):
            return [(idx, per_batch_fn(replicas[wi], ds))
                    for idx, ds in shards[wi]]

        with ThreadPoolExecutor(max_workers=n_workers) as pool:
            out = []
            for part in pool.map(run_shard, range(n_workers)):
                out.extend(part)
        return out

    def _replicas_for(self, n_workers: int) -> list:
        """Cached per-worker replica clones. A fresh `net.clone()` per
        `_shard_map` call paid init + param copy + a full re-trace of
        the replica's jitted eval EVERY epoch of an early-stopping loop;
        cached replicas keep their compiled executables, and a param
        sync (the net trained since last call — detected by params
        identity, every fit commits fresh arrays) just repoints each
        replica at the net's current params/state. Aliasing is safe:
        replicas only ever EVALUATE (no donation on the eval path), and
        the identity stamp re-syncs them before any use after the
        master's next training step."""
        if len(self._replica_cache) < n_workers:
            self._replica_cache.extend(
                self.net.clone()
                for _ in range(n_workers - len(self._replica_cache)))
            self._replica_params_ref = None  # new clones: force a sync
        if self._replica_params_ref is not self.net._params:
            for replica in self._replica_cache:
                replica._params = self.net._params
                replica._layer_state = self.net._layer_state
            self._replica_params_ref = self.net._params
        return self._replica_cache[:n_workers]

    def evaluate(self, data, labels: Optional[List[str]] = None,
                 top_n: int = 1):
        """Cluster-style evaluation: per-shard `Evaluation`s merged into
        one (reference `SparkDl4jMultiLayer.evaluate:511-528`). Confusion
        counts are additive, so the merged result equals single-device
        `net.evaluate` on the same data exactly."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        def eval_batch(replica, ds):
            # the replica's own evaluate handles container specifics
            # (MultiDataSet inputs, masks) for both MLN and CG
            return replica.evaluate(ds, labels=labels, top_n=top_n)

        merged = Evaluation(labels=labels, top_n=top_n)
        for _, ev in sorted(self._shard_map(data, eval_batch)):
            merged.merge(ev)
        return merged

    def calculate_score(self, data, average: bool = True) -> float:
        """Loss over the full dataset, computed shard-parallel and combined
        example-weighted (reference `SparkDl4jMultiLayer.calculateScore:382`
        — sum of per-partition scores, optionally / total examples)."""
        results = self._shard_map(
            data, lambda replica, ds: (replica.score(ds) * ds.num_examples(),
                                       ds.num_examples()))
        total = sum(s for _, (s, _) in results)
        n = sum(n for _, (_, n) in results)
        return float(total / n) if average and n else float(total)

    def score(self, ds) -> float:
        """Mean loss on one batch (reference `SparkDl4jMultiLayer.score`)."""
        return self.calculate_score(ds, average=True)

    def score_examples(self, data,
                       add_regularization: bool = False) -> np.ndarray:
        """Per-example scores over the dataset, shard-parallel, returned in
        the ORIGINAL example order (reference
        `SparkDl4jMultiLayer.scoreExamples:382-416`)."""
        results = self._shard_map(
            data,
            lambda replica, ds: replica.score_examples(
                ds, add_regularization=add_regularization))
        return np.concatenate([r for _, r in sorted(results)]) \
            if results else np.zeros((0,))

    def get_network(self):
        return self.net


class DistributedComputationGraph(DistributedMultiLayer):
    """Reference `spark/impl/graph/SparkComputationGraph.java`."""
