"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

No counterpart in the reference — its only strategy is data parallelism
(SURVEY §2.4: "The only parallelism strategy implemented anywhere is DATA
PARALLELISM") — but pipeline parallelism is a first-class axis of the TPU
design space (models deeper than one chip's HBM). Shape: S identical
stages' parameters are STACKED on axis 0 and sharded over the `pipe` mesh
axis (one stage per device); microbatches flow device→device via
`lax.ppermute` over ICI. The whole fill/steady/drain schedule runs inside
one jitted `fori_loop` — XLA overlaps each hop's DMA with the next stage's
compute.

Differentiable end-to-end: `jax.grad` through `ppermute` yields the
reverse-direction pipeline for the backward pass automatically.

Restriction: stages must be homogeneous (same param structure and same
activation shape in == out) — the transformer-block / MLP-stack case. The
heterogeneous-stage alternative is tensor/data sharding (ParallelWrapper).
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map


def pipeline_apply(block_fn: Callable, stacked_params, x: jnp.ndarray,
                   mesh: Mesh, *, axis_name: str = "pipe",
                   microbatches: int = None,
                   data_axis: str = None,
                   block_ctx: bool = False) -> jnp.ndarray:
    """Apply S stacked stages as a pipeline over the mesh axis.

    block_fn(params_i, x) -> y with y.shape == x.shape (homogeneous stages);
    stacked_params: pytree whose leaves have leading dim S (stage axis);
    x: (B, ...) global batch, split into `microbatches` equal chunks
    (default: S — the minimum for a full pipeline).

    `data_axis`: 2-D parallelism — each microbatch's batch dimension is
    additionally sharded over this mesh axis (dp x pp: the pipeline hops
    ride `axis_name` per data shard, activations never cross the data
    axis; gradient reduction over `data_axis` is inserted by the SPMD
    partitioner at the parameter level outside this function).

    `block_ctx`: call `block_fn(params_i, x, stage, row_offset)` instead —
    `stage` is this device's (traced) pipeline-stage index and
    `row_offset` the first GLOBAL batch-row index of the microbatch slice
    `x` holds. Lets the block derive per-layer PRNG keys (fold the true
    layer index) and partition-invariant dropout masks (`ops/rng_rows`).

    Tensor parallelism composes through the AUTO mesh axes: only
    `axis_name` (and `data_axis`) are manual inside the shard_map — any
    other mesh axis (e.g. 'model') is left to the SPMD partitioner, so
    stacked param leaves sharded P(pipe, ..., 'model') at the jit level
    keep their tensor sharding inside each stage and XLA inserts the
    model-axis collectives (3-D dp x tp x pp in one mesh).
    """
    S = mesh.shape[axis_name]
    M = microbatches if microbatches is not None else S
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    if data_axis is not None:
        if data_axis == axis_name:
            raise ValueError(
                f"data_axis must differ from the pipeline axis "
                f"{axis_name!r}: sharding the batch over the axis the "
                "stage loop ppermutes over would silently mix shards")
        if data_axis not in mesh.shape:
            raise ValueError(f"mesh has no {data_axis!r} axis: "
                             f"{dict(mesh.shape)}")
        if (B // M) % mesh.shape[data_axis] != 0:
            raise ValueError(
                f"microbatch size {B // M} not divisible over data axis "
                f"'{data_axis}' of size {mesh.shape[data_axis]}")
    leaf = jax.tree_util.tree_leaves(stacked_params)[0]
    if leaf.shape[0] != S:
        raise ValueError(
            f"stacked params have {leaf.shape[0]} stages but mesh axis "
            f"'{axis_name}' has size {S}")
    xs = x.reshape(M, B // M, *x.shape[1:])

    def local(stage_p, xs_local):
        # stage_p leaves: (1, ...) — this device's stage; drop the stage dim
        p = jax.tree.map(lambda a: a[0], stage_p)
        d = lax.axis_index(axis_name)
        perm = [(i, (i + 1) % S) for i in range(S)]
        mb_shape = xs_local.shape[1:]
        n_steps = S + M - 1
        # global row offset of this device's slice of microbatch m:
        # m * (global microbatch rows) + this data shard's offset within it
        local_rows = xs_local.shape[1]
        di_rows = (lax.axis_index(data_axis) * local_rows
                   if data_axis is not None else 0)

        def step(t, carry):
            buf, outs = carry
            # device 0 injects microbatch t (clamped; masked later), others
            # consume what arrived from the previous stage
            inj = xs_local[jnp.clip(t, 0, M - 1)]
            inp = jnp.where(d == 0, inj, buf)
            if block_ctx:
                # stage d processes microbatch t - d at tick t (garbage
                # during fill/drain; outputs masked below)
                m = jnp.clip(t - d, 0, M - 1)
                y = block_fn(p, inp, d, m * (B // M) + di_rows)
            else:
                y = block_fn(p, inp)
            # last stage owns the finished microbatch t-(S-1)
            out_idx = t - (S - 1)
            oc = jnp.clip(out_idx, 0, M - 1)
            take = (d == S - 1) & (out_idx >= 0)
            outs = outs.at[oc].set(jnp.where(take, y, outs[oc]))
            buf_next = lax.ppermute(y, axis_name, perm)
            return buf_next, outs

        init = (jnp.zeros(mb_shape, xs_local.dtype),
                jnp.zeros_like(xs_local))
        _, outs = lax.fori_loop(0, n_steps, step, init)
        # results live on the last stage's device: masked psum broadcasts
        # them to every device (replicated output spec)
        return lax.psum(jnp.where(d == S - 1, outs, 0.0), axis_name)

    # batch dim of each microbatch rides the data axis (if any); the
    # stage loop and collectives above only ever name `axis_name`, so the
    # same body serves 1-D pp and 2-D dp x pp. Any OTHER mesh axis stays
    # AUTO (partial-manual shard_map): tensor-sharded stage params keep
    # their model-axis sharding inside the body and the SPMD partitioner
    # inserts the tensor collectives — pp composes with tp for free.
    xspec = P(None, data_axis) if data_axis is not None else P()
    manual = {axis_name} | ({data_axis} if data_axis is not None else set())
    extra = set(mesh.axis_names) - manual
    kw = {"axis_names": frozenset(manual)} if extra else {}
    out = shard_map(local, mesh=mesh,
                    in_specs=(P(axis_name), xspec),
                    out_specs=xspec, check_vma=False, **kw)(stacked_params, xs)
    return out.reshape(B, *x.shape[1:])


def stack_stage_params(per_stage_params) -> object:
    """[stage0_pytree, stage1_pytree, ...] (identical structures) → one
    pytree with a leading stage axis, ready to shard over `pipe`."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def shard_stacked_params(stacked_params, mesh: Mesh,
                         axis_name: str = "pipe"):
    """Place each stage's slice on its pipeline device."""
    sh = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda a: jax.device_put(a, sh), stacked_params)
