"""Expert parallelism: Switch-style top-1 mixture-of-experts over a mesh
axis.

No counterpart in the reference (DP-only, SURVEY §2.4); included because
expert parallelism is the remaining first-class axis of the TPU sharding
design space (dp/tp/sp/pp/ep). Design: E experts' FFN parameters are
STACKED and sharded one-per-device over the `expert` mesh axis; a linear
router picks top-1 per token; tokens travel to their expert's device via
`lax.all_to_all` over ICI (the standard MoE dispatch collective), are
processed in one batched expert matmul, and return the same way.

Capacity: each expert processes at most `capacity = ceil(tokens/E) *
capacity_factor` tokens per device-shard; overflow tokens pass through
unchanged (Switch Transformer semantics). Everything is static-shaped —
routing is by sort/scatter, no data-dependent control flow.
"""
from __future__ import annotations

import contextlib
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# -- expert-parallel mesh scope ------------------------------------------
# ParallelWrapper enters this scope inside its (traced) step so that
# MoELayer.forward — which has no mesh in its signature — can discover the
# mesh and route through the all_to_all path. Trace-time state: the scope
# is active while jit traces the step, and costs nothing afterwards.
_MESH_SCOPE: list = []


@contextlib.contextmanager
def expert_mesh_scope(mesh: Mesh, data_axis: Optional[str] = None):
    """Declare the active mesh (and its data axis, if any) for expert-
    parallel MoE layers traced within the scope."""
    _MESH_SCOPE.append((mesh, data_axis))
    try:
        yield
    finally:
        _MESH_SCOPE.pop()


def current_expert_mesh() -> Optional[Tuple[Mesh, Optional[str]]]:
    return _MESH_SCOPE[-1] if _MESH_SCOPE else None


def router_probs(x: jnp.ndarray, router_w: jnp.ndarray) -> jnp.ndarray:
    """(N, D) tokens × (D, E) router → (N, E) softmax probabilities."""
    return jax.nn.softmax(x @ router_w, axis=-1)


def _dispatch_indices(expert_idx: jnp.ndarray, E: int, capacity: int,
                      valid=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Position of each token within its expert's capacity buffer, and a
    keep-mask for tokens under capacity. `valid` (N,) bool excludes tokens
    (padding) from dispatch AND from capacity accounting."""
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # (N, E)
    if valid is not None:
        onehot = onehot * valid[:, None].astype(jnp.int32)
    pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot      # 1-based
    pos = jnp.max(pos_in_expert, axis=-1) - 1                # (N,)
    keep = pos < capacity
    if valid is not None:
        keep = keep & (pos >= 0)  # invalid tokens have pos == -1
    return pos, keep


def moe_apply_reference(expert_fn: Callable, stacked_params, x: jnp.ndarray,
                        router_w: jnp.ndarray, *,
                        capacity_factor: float = 1.25,
                        token_mask=None,
                        passthrough: str = "identity",
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Single-device reference semantics (also the parity baseline for the
    sharded path): top-1 routing with capacity, overflow passes through.

    `token_mask` (N,) with 1=real: padding tokens bypass the experts
    entirely — no routing, no capacity consumption, no weight in the
    load-balancing loss.

    `passthrough` is what dropped (overflow/masked) tokens yield:
    "identity" → the input token (a layer with no external residual, e.g.
    MoELayer, leaves them unchanged); "zero" → 0, for callers that add
    their own residual (TransformerBlock's `x + ffn`) — identity there
    would double-add the input.

    Returns (y, aux_loss) — aux_loss is the Switch load-balancing loss
    (mean fraction routed × mean router prob, scaled by E)."""
    N, D = x.shape
    E = router_w.shape[1]
    capacity = int(np.ceil(N / E * capacity_factor))
    probs = router_probs(x, router_w)
    expert_idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]
    valid = None if token_mask is None else token_mask > 0
    pos, keep = _dispatch_indices(expert_idx, E, capacity, valid)  # global cap

    # scatter tokens into (E, capacity, D) buffers
    buf = jnp.zeros((E, capacity, D), x.dtype)
    safe_pos = jnp.where(keep, pos, 0)
    buf = buf.at[expert_idx, safe_pos].add(
        jnp.where(keep[:, None], x, 0.0))
    # one batched expert application: vmap over the expert axis
    out_buf = jax.vmap(expert_fn)(stacked_params, buf)
    # gather back
    y_expert = out_buf[expert_idx, safe_pos]
    if passthrough not in ("identity", "zero"):
        raise ValueError(f"unknown passthrough {passthrough!r}")
    dropped = x if passthrough == "identity" else jnp.zeros_like(x)
    y = jnp.where(keep[:, None], gate[:, None] * y_expert, dropped)

    # load-balancing loss (Switch eq. 4) over REAL tokens only
    oh = jax.nn.one_hot(expert_idx, E)
    if valid is not None:
        w = valid.astype(x.dtype)
        denom = jnp.maximum(jnp.sum(w), 1.0)
        frac_routed = jnp.sum(oh * w[:, None], axis=0) / denom
        mean_prob = jnp.sum(probs * w[:, None], axis=0) / denom
    else:
        frac_routed = jnp.mean(oh, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_routed * mean_prob)
    return y, aux


def moe_apply(expert_fn: Callable, stacked_params, x: jnp.ndarray,
              router_w: jnp.ndarray, mesh: Mesh, *,
              axis_name: str = "expert", capacity_factor: float = 1.25,
              passthrough: str = "identity",
              data_axis: Optional[str] = None,
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel MoE: experts sharded over `axis_name`, token
    dispatch/return via all_to_all. `passthrough` as in
    `moe_apply_reference` ("zero" for callers with an external residual).

    Matches `moe_apply_reference` exactly while no expert overflows
    (parity-tested). UNDER OVERFLOW the two drop different tokens: here
    capacity is enforced per (expert, source-device) slice — the
    GShard-style static dispatch shape that keeps the all_to_all regular —
    while the reference caps each expert globally in token order. Both are
    valid Switch semantics; don't expect bitwise agreement when routing is
    skewed and capacity is tight.

    `data_axis`: composes ep with data parallelism on a 2-D mesh — tokens
    shard over (data_axis, axis_name) jointly, the all_to_all rides the
    expert axis within each data row, and the load-balancing loss means
    over both axes (the network path ParallelWrapper drives).

    x: (N, D) tokens (flatten (B, T, D) first); stacked_params: pytree with
    leading expert dim E == mesh axis size; router_w: (D, E).
    """
    E = mesh.shape[axis_name]
    leaf = jax.tree_util.tree_leaves(stacked_params)[0]
    if leaf.shape[0] != E:
        raise ValueError(f"{leaf.shape[0]} experts but mesh axis "
                         f"'{axis_name}' has size {E}")
    dp = mesh.shape.get(data_axis, 1) if data_axis else 1
    N, D = x.shape
    if N % (E * dp):
        raise ValueError(f"token count {N} not divisible by expert axis "
                         f"{E} x data axis {dp}")
    # capacity derives from the tokens ONE DATA ROW routes among E experts
    # (dp=1 reduces to the global formula)
    capacity = int(np.ceil(N / dp / E * capacity_factor))
    # per-device capacity slice must be whole
    capacity = int(np.ceil(capacity / E) * E)
    if passthrough not in ("identity", "zero"):
        raise ValueError(f"unknown passthrough {passthrough!r}")
    reduce_axes = (data_axis, axis_name) if dp > 1 else axis_name

    def local(stage_p, x_local, rw):
        # x_local: (N/E, D) this device's token shard; stage_p: this
        # device's expert params (leading dim 1)
        p = jax.tree.map(lambda a: a[0], stage_p)
        probs = router_probs(x_local, rw)              # (n, E)
        expert_idx = jnp.argmax(probs, axis=-1)
        gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]
        cap_local = capacity // E  # per (expert, source-device) slots
        pos, keep = _dispatch_indices(expert_idx, E, cap_local)
        safe_pos = jnp.where(keep, pos, 0)
        buf = jnp.zeros((E, cap_local, x_local.shape[1]), x_local.dtype)
        buf = buf.at[expert_idx, safe_pos].add(
            jnp.where(keep[:, None], x_local, 0.0))
        # all_to_all: (E, cap_local, D) -> expert e's device receives every
        # source's slice for e: (E_src, cap_local, D) concat on axis 0
        recv = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
        out = expert_fn(p, recv.reshape(-1, recv.shape[-1]))
        out = out.reshape(E, cap_local, -1)
        back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
        y_expert = back[expert_idx, safe_pos]
        dropped = (x_local if passthrough == "identity"
                   else jnp.zeros_like(x_local))
        y = jnp.where(keep[:, None], gate[:, None] * y_expert, dropped)
        frac = jnp.mean(jax.nn.one_hot(expert_idx, E), axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        aux = E * jnp.sum(lax.pmean(frac, reduce_axes)
                          * lax.pmean(mean_prob, reduce_axes))
        return y, aux

    tok = P((data_axis, axis_name)) if dp > 1 else P(axis_name)
    y, aux = shard_map(local, mesh=mesh,
                       in_specs=(P(axis_name), tok, P()),
                       out_specs=(tok, P()), check_vma=False)(
        stacked_params, x, router_w)
    return y, aux


def switch_ffn_sharded(params, tokens: jnp.ndarray, mesh: Mesh, *,
                       axis_name: str, data_axis: Optional[str],
                       act: Callable, capacity_factor: float,
                       aux_weight: float, train: bool = False,
                       passthrough: str = "identity") -> jnp.ndarray:
    """Expert-PARALLEL twin of `switch_ffn`: same stacked router/W1/b1/W2/b2
    params and aux-loss contract, dispatch through `moe_apply`'s
    all_to_all over `axis_name` (composing with data parallelism over
    `data_axis`). This is the network-step path MoELayer(expert_axis=...)
    takes under ParallelWrapper."""
    from deeplearning4j_tpu.ops.aux_loss import add_aux_loss

    def expert_fn(p, t):
        return act(t @ p["W1"] + p["b1"]) @ p["W2"] + p["b2"]

    stacked = {"W1": params["W1"], "b1": params["b1"],
               "W2": params["W2"], "b2": params["b2"]}
    y, aux = moe_apply(expert_fn, stacked, tokens, params["router"], mesh,
                       axis_name=axis_name, data_axis=data_axis,
                       capacity_factor=capacity_factor,
                       passthrough=passthrough)
    if train:
        add_aux_loss(aux_weight * aux)
    return y


def switch_ffn(params, tokens: jnp.ndarray, *, act: Callable,
               capacity_factor: float, aux_weight: float,
               token_mask=None, train: bool = False,
               passthrough: str = "identity") -> jnp.ndarray:
    """Shared Switch-MoE FFN dispatch used by MoELayer and
    TransformerBlock's MoE branch (one implementation, one behavior):
    params needs router/W1/b1/W2/b2 (experts stacked on axis 0); the
    load-balancing aux loss is contributed via ops/aux_loss when training."""
    from deeplearning4j_tpu.ops.aux_loss import add_aux_loss

    def expert_fn(p, t):
        return act(t @ p["W1"] + p["b1"]) @ p["W2"] + p["b2"]

    stacked = {"W1": params["W1"], "b1": params["b1"],
               "W2": params["W2"], "b2": params["b2"]}
    y, aux = moe_apply_reference(expert_fn, stacked, tokens,
                                 params["router"],
                                 capacity_factor=capacity_factor,
                                 token_mask=token_mask,
                                 passthrough=passthrough)
    if train:
        add_aux_loss(aux_weight * aux)
    return y
