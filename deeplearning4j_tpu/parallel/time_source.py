"""Cross-node time sources for training stats alignment.

Reference: `spark/time/TimeSource.java` / `NTPTimeSource.java` /
`TimeSourceProvider.java` (SURVEY §2.4) — executors stamp their stats with
NTP-corrected time so the driver can align per-phase timelines across
nodes. Equivalents here: `SystemTimeSource` (wall clock),
`MonotonicTimeSource` (drift-free intervals with a wall-clock anchor), and
`NTPTimeSource` (SNTP query when the network allows it — this build
environment has zero egress, so construction fails fast unless an offset
is injected, e.g. measured out-of-band by the cluster launcher).
"""
from __future__ import annotations

import os
import struct
import time
from typing import Optional


class TimeSource:
    """`current_time_millis()` contract (reference `TimeSource.java`)."""

    def current_time_millis(self) -> int:
        raise NotImplementedError


class SystemTimeSource(TimeSource):
    def current_time_millis(self) -> int:
        return int(time.time() * 1000)


class MonotonicTimeSource(TimeSource):
    """Wall-clock anchor + monotonic deltas: immune to NTP step
    adjustments mid-run (interval math stays consistent)."""

    def __init__(self):
        self._anchor_wall_ms = time.time() * 1000.0
        self._anchor_mono = time.monotonic()

    def current_time_millis(self) -> int:
        return int(self._anchor_wall_ms
                   + (time.monotonic() - self._anchor_mono) * 1000.0)


class NTPTimeSource(TimeSource):
    """SNTP-corrected clock (reference `NTPTimeSource.java`).

    `offset_ms` injects a known offset without any network IO. Otherwise a
    single SNTP query runs against `server` at construction; environments
    without egress get an immediate OSError instead of a silent wrong
    clock."""

    NTP_EPOCH_DELTA = 2208988800  # 1900 → 1970 seconds

    def __init__(self, server: str = "pool.ntp.org", port: int = 123,
                 timeout: float = 5.0, offset_ms: Optional[float] = None):
        if offset_ms is not None:
            self.offset_ms = float(offset_ms)
        else:
            self.offset_ms = self._query_offset(server, port, timeout)
        self._base = MonotonicTimeSource()

    @staticmethod
    def _query_offset(server: str, port: int, timeout: float) -> float:
        import socket

        pkt = b"\x1b" + 47 * b"\0"
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.settimeout(timeout)
            t0 = time.time()
            s.sendto(pkt, (server, port))
            data, _ = s.recvfrom(512)
            t3 = time.time()
        secs, frac = struct.unpack("!II", data[40:48])
        server_time = secs - NTPTimeSource.NTP_EPOCH_DELTA + frac / 2 ** 32
        # offset per SNTP with t1≈t2≈server_time: midpoint correction
        return ((server_time - t0) + (server_time - t3)) / 2.0 * 1000.0

    def current_time_millis(self) -> int:
        return int(self._base.current_time_millis() + self.offset_ms)


class TimeSourceProvider:
    """Picks the time source (reference `TimeSourceProvider.java`: system
    property `timesource`; here env var `DL4J_TPU_TIMESOURCE` =
    system|monotonic|ntp)."""

    _instance: Optional[TimeSource] = None

    @classmethod
    def get_instance(cls) -> TimeSource:
        if cls._instance is None:
            kind = os.environ.get("DL4J_TPU_TIMESOURCE", "monotonic").lower()
            if kind == "system":
                cls._instance = SystemTimeSource()
            elif kind == "ntp":
                cls._instance = NTPTimeSource()
            else:
                cls._instance = MonotonicTimeSource()
        return cls._instance

    @classmethod
    def reset(cls) -> None:
        cls._instance = None
