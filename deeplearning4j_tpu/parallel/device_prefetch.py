"""Device-side batch prefetch (reference `parallelism/MagicQueue.java:21` —
the device-aware multi-queue that stages each mini-batch on its target GPU
before the worker needs it).

TPU equivalent: `DevicePrefetchIterator` wraps any DataSetIterator and
`jax.device_put`s upcoming batches (optionally with a mesh sharding) a few
steps ahead. `device_put` is asynchronous, so the host→HBM DMA of batch
N+k overlaps the compiled step for batch N; the training loop then passes
already-resident arrays to the jitted step instead of paying the transfer
on the critical path.

Opt-in, not the default: over a REMOTE device transport (this build's
axon tunnel) each device_put is its own round trip and measured ~25%
SLOWER than letting the jitted call carry the batch (347k → 258k
samples/s, LeNet@512); on locally-attached chips the overlap wins. Use it
when profiling shows H2D on the critical path.
"""
from __future__ import annotations

from collections import deque
from typing import Iterator, Optional

import jax
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


class DevicePrefetchIterator(DataSetIterator):
    """Yields DataSets whose arrays are already device-resident.

    `sharding`: optional `jax.sharding.Sharding` for the batch axis (e.g.
    `NamedSharding(mesh, P("data"))`) — batches land pre-sharded across the
    mesh, so the sharded step consumes them without a relayout.
    `depth`: how many batches to keep in flight ahead of the consumer.
    """

    def __init__(self, underlying: DataSetIterator, depth: int = 2,
                 sharding=None):
        self._under = underlying
        self.depth = max(1, depth)
        self.sharding = sharding
        self._fifo: deque = deque()
        self._iter: Optional[Iterator[DataSet]] = None

    def _put(self, a):
        if a is None:
            return None
        arr = np.asarray(a)  # dtype preserved: the step casts if it wants to
        if self.sharding is not None:
            return jax.device_put(arr, self.sharding)
        return jax.device_put(arr)

    def _stage(self, ds: DataSet) -> DataSet:
        return DataSet(self._put(ds.features), self._put(ds.labels),
                       self._put(ds.features_mask), self._put(ds.labels_mask))

    def _refill(self):
        while len(self._fifo) < self.depth:
            try:
                ds = next(self._iter)
            except StopIteration:
                return
            self._fifo.append(self._stage(ds))

    def reset(self) -> None:
        self._iter = iter(self._under)
        self._fifo.clear()
        self._refill()

    def has_next(self) -> bool:
        if self._iter is None:
            self.reset()
        return bool(self._fifo)

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds = self._fifo.popleft()
        self._refill()
        return ds

    def batch(self) -> int:
        return self._under.batch()

    @property
    def async_supported(self) -> bool:
        # already ahead-of-time; wrapping in the host-thread prefetcher too
        # would just add queue handoffs
        return False
