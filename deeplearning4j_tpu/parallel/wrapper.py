"""ParallelWrapper: multi-chip data-parallel (+ optional tensor-parallel)
training.

Reference: `deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java`
— worker threads each holding a model replica on its own GPU, barrier-join
every `averagingFrequency` iterations, then
`Nd4j.averageAndPropagate(params)` (:179) and updater-state averaging (:212).

TPU-native redesign: there are no replica threads and no explicit averaging
step. The SAME jitted train step is compiled over a `Mesh` with the batch
sharded on the `data` axis and params replicated (or sharded per
`param_specs` for tensor parallelism). XLA's SPMD partitioner inserts the
gradient all-reduce (psum over ICI) INSIDE the compiled step, so "averaging
frequency" is every step at near-zero cost, params/updater state never leave
the device, and loss curves match single-chip training exactly (same-seed
parity test — the analogue of the reference's
`TestCompareParameterAveragingSparkVsSingleMachine`).
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.parallel.mesh import make_mesh

logger = logging.getLogger("deeplearning4j_tpu")


class ParallelWrapper:
    """Usage (mirrors the reference's builder):

        pw = ParallelWrapper(net)            # DP over all devices
        pw.fit(iterator, epochs=...)

    `param_specs`: optional {layer_index: {param_name: PartitionSpec}} to
    shard specific parameters over a `model` mesh axis (tensor parallelism —
    capability beyond the reference, which is DP-only per SURVEY §2.4).
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 data_axis: str = "data",
                 param_specs: Optional[Dict[int, Dict[str, P]]] = None,
                 prefetch_buffer: int = 2):
        net._ensure_init()
        self.net = net
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        self.prefetch_buffer = prefetch_buffer
        self._repl = NamedSharding(self.mesh, P())
        self._batch_sh = NamedSharding(self.mesh, P(data_axis))

        # per-parameter shardings (default: replicated). Params are a LIST of
        # per-layer dicts for MultiLayerNetwork and a DICT keyed by vertex
        # name for ComputationGraph — param_specs keys follow the same scheme
        # (layer index or vertex name).
        specs = {k: dict(v) for k, v in (param_specs or {}).items()}

        # expert parallelism as a network feature: a MoELayer carrying
        # expert_axis gets its stacked expert weights sharded one-per-device
        # over that axis (router replicated), and the step is traced inside
        # expert_mesh_scope so the layer routes via moe_apply's all_to_all
        # (reference seam analogue: `ParallelWrapper.java:46-52` — every
        # parallelism axis hangs off the unchanged user API)
        self._expert_layers = []
        self._expert_axes = set()

        def _wire_expert(key, layer):
            """Validate + shard one expert-parallel MoE layer. `key` is the
            param_specs key: layer index (MLN) or vertex name (CG) — the
            sharding map below is keyed the same way, so both containers
            ride the identical seam (reference analogue:
            `ComputationGraph.java:952` treats both containers uniformly)."""
            ax = layer.expert_axis
            if ax not in self.mesh.shape:
                raise ValueError(
                    f"layer {key!r} wants expert_axis '{ax}' but the mesh "
                    f"axes are {dict(self.mesh.shape)}")
            if layer.n_experts != self.mesh.shape[ax]:
                raise ValueError(
                    f"layer {key!r} has {layer.n_experts} experts but mesh "
                    f"axis '{ax}' has size {self.mesh.shape[ax]} — expert-"
                    f"parallel execution shards one expert per device")
            self._expert_layers.append(key)
            self._expert_axes.add(ax)
            ep = specs.setdefault(key, {})
            for name in ("W1", "b1", "W2", "b2"):
                ep.setdefault(name, P(ax))

        if isinstance(net._params, dict):
            # ComputationGraph: layer vertices carry the same MoELayer; the
            # expert scope + switch_ffn_sharded path is container-agnostic
            # (MoELayer.forward consults the scope), so only the sharding
            # keys differ — vertex names instead of layer indices (r5)
            for name, node in getattr(net.conf, "nodes", {}).items():
                if (getattr(node, "is_layer", False)
                        and getattr(node.layer, "expert_axis", None)):
                    _wire_expert(name, node.layer)
        for i, layer in enumerate(getattr(net, "layers", []) or []):
            if getattr(layer, "expert_axis", None):
                _wire_expert(i, layer)
        if self._expert_layers and net.conf.tbptt_fwd_length > 0:
            # tBPTT pads the tail window with a synthesized mask, which the
            # expert-parallel path rejects — mid-epoch, after partial
            # updates. Reject the combination up front instead.
            raise NotImplementedError(
                "expert_axis with truncated BPTT is not supported yet "
                "(the padded tail window is masked, and masked tokens "
                "cannot ride the expert-parallel dispatch) — drop "
                "expert_axis or disable tbptt")

        def _layer_sh(key, p):
            return {name: NamedSharding(self.mesh, specs.get(key, {}).get(name, P()))
                    for name in p}

        if isinstance(net._params, dict):
            items = net._params.items()
            self._param_sh = {k: _layer_sh(k, p) for k, p in items}
            self._upd_sh = {
                k: {name: {s: self._param_sh[k][name] for s in u}
                    for name, u in upd_k.items()}
                for k, upd_k in net._upd_state.items()}
        else:
            self._param_sh = [_layer_sh(i, p) for i, p in enumerate(net._params)]
            # updater state mirrors its parameter's sharding
            self._upd_sh = [
                {name: {s: self._param_sh[i][name] for s in u}
                 for name, u in upd_i.items()}
                for i, upd_i in enumerate(net._upd_state)]
        self._lstate_sh = jax.tree.map(lambda _: self._repl, net._layer_state)

        # place the existing params on the mesh
        net._params = jax.device_put(net._params, self._param_sh)
        net._upd_state = jax.device_put(net._upd_state, self._upd_sh)
        net._layer_state = jax.device_put(net._layer_state, self._lstate_sh)

        self._jit_step_tbptt = None
        self._tbptt_lstate_sh = None
        step = self._with_expert_scope(self._wrap_step(net.train_step_fn()))
        self._jit_step = jax.jit(
            step,
            in_shardings=(self._param_sh, self._upd_sh, self._lstate_sh,
                          self._repl) + self._batch_shardings(),
            out_shardings=(self._param_sh, self._upd_sh, self._lstate_sh,
                           self._repl, self._repl),
            donate_argnums=(0, 1, 2, 3),
        )

    def get_network(self):
        """The wrapped network — the same accessor `DistributedMultiLayer`
        exposes, so `FaultTolerantTrainer` can drive either handle's fit
        while checkpointing/restoring the underlying net."""
        return self.net

    # -- sharded checkpointing ---------------------------------------------
    def save_checkpoint(self, path) -> None:
        """Write params/updater/layer state shard-by-shard via orbax — no
        full-model host gather (see `util/sharded_checkpoint`)."""
        from deeplearning4j_tpu.util.sharded_checkpoint import (
            save_sharded_checkpoint,
        )

        save_sharded_checkpoint(path, self.net)

    def load_checkpoint(self, path) -> None:
        """Restore onto THIS wrapper's mesh/shardings — a checkpoint saved
        from a different mesh layout reshards on load."""
        from deeplearning4j_tpu.util.sharded_checkpoint import (
            restore_sharded_checkpoint,
        )

        restore_sharded_checkpoint(
            path, self.net,
            shardings=(self._param_sh, self._upd_sh, self._lstate_sh))

    # subclass hooks (SequenceParallelWrapper overrides both) --------------
    def _wrap_step(self, step):
        return step

    def _with_expert_scope(self, step):
        """Trace the step inside expert_mesh_scope when the net has
        expert-parallel MoE layers (the scope is consulted at trace time;
        compiled steps carry no runtime cost)."""
        if not self._expert_layers:
            return step
        from deeplearning4j_tpu.parallel.experts import expert_mesh_scope

        data_axis = (self.data_axis if self.data_axis in self.mesh.shape
                     else None)

        def scoped(*args):
            with expert_mesh_scope(self.mesh, data_axis):
                return step(*args)
        return scoped

    def _batch_shardings(self):
        """(features, labels, fmask, lmask) shardings."""
        return (self._batch_sh,) * 4

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def _shard_batch(self, ds):
        """Trim the batch to a multiple of the data-axis size (DataSet or
        MultiDataSet). With expert-parallel layers the token count must
        also divide by every expert axis x dp (moe_apply's all_to_all is
        static-shaped), so trim further until B*T satisfies it — otherwise
        an uneven final iterator batch would crash mid-epoch."""
        n_data = self.mesh.shape.get(self.data_axis, 1)
        B = ds.num_examples()
        usable = (B // n_data) * n_data
        if self._expert_layers and usable:
            f = ds.features[0] if isinstance(ds.features, list) else ds.features
            # time length: (B, T, F) dense sequences, or (B, T) integer
            # token ids (TokenEmbedding nets) — for the latter dim 1 is
            # TIME, not features, and counting it as 1 would over-trim
            # batches whose true token count B*T already divides. For a
            # ComputationGraph 2-D input, T=1 is the safe (stricter) choice:
            # need | B implies need | B*T, so the trim stays valid.
            first = (self.net.layers[0]
                     if getattr(self.net, "layers", None) else None)
            int_ids = (f.ndim == 2 and first is not None
                       and getattr(first, "integer_input", False))
            T = f.shape[1] if (f.ndim == 3 or int_ids) else 1
            need = n_data
            for ax in self._expert_axes:
                need = int(np.lcm(need, self.mesh.shape[ax] * n_data))
            while usable and (usable * T) % need:
                usable -= n_data
        if usable == 0:
            logger.warning("dropping batch of %d < %d devices", B, n_data)
            return None
        if usable != B:
            logger.warning("trimming batch %d -> %d (divisibility by %d)",
                           B, usable, n_data)
        if usable == B:
            return ds

        def sl(a):
            return None if a is None else a[:usable]

        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        if isinstance(ds, MultiDataSet):
            return MultiDataSet(
                features=[f[:usable] for f in ds.features],
                labels=[l[:usable] for l in ds.labels],
                features_masks=None if ds.features_masks is None else [sl(m) for m in ds.features_masks],
                labels_masks=None if ds.labels_masks is None else [sl(m) for m in ds.labels_masks])
        return DataSet(ds.features[:usable], sl(ds.labels),
                       sl(ds.features_mask), sl(ds.labels_mask))

    def fit(self, data: Union[DataSet, DataSetIterator], epochs: int = 1) -> None:
        """Sharded training loop (reference `ParallelWrapper.fit:322`)."""
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        net = self.net
        if isinstance(data, (DataSet, MultiDataSet)):
            iterator: DataSetIterator = ListDataSetIterator([data])
        else:
            iterator = data
        if iterator.async_supported and not isinstance(iterator, AsyncDataSetIterator):
            iterator = AsyncDataSetIterator(iterator, self.prefetch_buffer)
        tbptt = net.conf.tbptt_fwd_length > 0
        net._it_device = jax.device_put(
            jnp.asarray(net.iteration, jnp.int32), self._repl)
        for _ in range(epochs):
            for listener in net.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(net)
            for ds in iterator:
                ds = self._shard_batch(ds)
                if ds is None:
                    continue
                if tbptt and net._tbptt_applicable(ds):
                    self._fit_tbptt(ds)
                    continue
                net._validate_labels(ds)
                f, l, fm, lm = net._batch_arrays(ds)
                (net._params, net._upd_state, net._layer_state, net._it_device,
                 loss) = self._jit_step(
                    net._params, net._upd_state, net._layer_state,
                    net._it_device, f, l, fm, lm)
                net._score = loss  # device array; synced lazily on read
                net.iteration += 1
                for listener in net.listeners:
                    if hasattr(listener, "record_batch"):
                        listener.record_batch(ds.num_examples())
                    listener.iteration_done(net, net.iteration)
            for listener in net.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(net)
            net.epoch += 1

    # -- data-parallel truncated BPTT --------------------------------------
    def _fit_tbptt(self, ds) -> None:
        """Truncated BPTT with the window step sharded over the mesh
        (BASELINE configs 3x5 composed: recurrent + data-parallel). The
        per-example LSTM (h, c) carries are sharded on the data axis like
        the batch itself, so the carry never crosses devices — only the
        gradient psum does (reference analogue:
        `ParallelWrapper.java:322` + `MultiLayerNetwork.java:1140`)."""
        net = self.net
        saved = net._tbptt_seed_carries(ds.num_examples())
        if self._jit_step_tbptt is None:
            # lstate shardings for the SEEDED structure: (B, n) carries ride
            # the data axis, everything else keeps its original placement
            lstate_sh = (list(self._lstate_sh)
                         if isinstance(self._lstate_sh, list)
                         else dict(self._lstate_sh))
            for key in saved:
                lstate_sh[key] = {"h": self._batch_sh, "c": self._batch_sh}
            self._tbptt_lstate_sh = lstate_sh
            step = self._with_expert_scope(
                self._wrap_step(net.train_step_fn()))
            self._jit_step_tbptt = jax.jit(
                step,
                in_shardings=(self._param_sh, self._upd_sh, lstate_sh,
                              self._repl) + self._batch_shardings(),
                out_shardings=(self._param_sh, self._upd_sh, lstate_sh,
                               self._repl, self._repl),
                donate_argnums=(0, 1, 2, 3),
            )
        net._layer_state = jax.device_put(net._layer_state,
                                          self._tbptt_lstate_sh)
        losses = []
        for window in net._tbptt_windows(ds):
            net._validate_labels(window)
            f, l, fm, lm = net._batch_arrays(window)
            (net._params, net._upd_state, net._layer_state, net._it_device,
             loss) = self._jit_step_tbptt(
                net._params, net._upd_state, net._layer_state,
                net._it_device, f, l, fm, lm)
            losses.append(loss)
            net.iteration += 1
            for listener in net.listeners:
                if hasattr(listener, "record_batch"):
                    listener.record_batch(window.num_examples())
                listener.iteration_done(net, net.iteration)
        net.score_value = float(np.mean([np.asarray(l) for l in losses]))
        # carries are per-batch transients; restore the persistent slots
        net._tbptt_restore_carries(saved)
