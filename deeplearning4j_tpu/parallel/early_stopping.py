"""Early stopping over multi-chip training.

Reference: `deeplearning4j-scaleout-parallelwrapper/.../
EarlyStoppingParallelTrainer.java` — the early-stopping epoch loop where
each epoch's fit runs through ParallelWrapper instead of single-device
`net.fit` — and `spark/earlystopping/SparkEarlyStoppingTrainer.java`,
the same loop driving the TrainingMaster's worker/averaging path.
"""
from __future__ import annotations

from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


class _FitFacade:
    """Presents a (fit delegate + net) pair as a single model: `fit` runs
    the delegate's multi-device path (ParallelWrapper sharded step, or the
    TrainingMaster's worker pool); everything else (score, listeners,
    serialization, clone) proxies to the underlying network — so model
    savers store real network clones, never the facade."""

    def __init__(self, fit_target, net):
        object.__setattr__(self, "_fit_target", fit_target)
        object.__setattr__(self, "_net", net)

    def fit(self, iterator, epochs: int = 1):
        object.__getattribute__(self, "_fit_target").fit(iterator,
                                                         epochs=epochs)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_net"), name)

    def __setattr__(self, name, value):
        setattr(object.__getattribute__(self, "_net"), name, value)


class EarlyStoppingDistributedTrainer(EarlyStoppingTrainer):
    """Early stopping where each epoch's fit goes through the
    TrainingMaster's worker/averaging path (reference
    `spark/earlystopping/SparkEarlyStoppingTrainer.java` — extends
    `BaseSparkEarlyStoppingTrainer.fit`: per-epoch
    `trainingMaster.executeTraining`, then score calculators / termination
    conditions on the synced net). Iteration-level termination conditions
    fire through the master's `iteration_done` listener fan-out, exactly
    as on the single-device trainer."""

    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_iterator, training_master):
        from deeplearning4j_tpu.parallel.training_master import (
            DistributedMultiLayer,
        )

        self.distributed = (
            training_master if isinstance(training_master,
                                          DistributedMultiLayer)
            else DistributedMultiLayer(net, training_master))
        super().__init__(config,
                         _FitFacade(self.distributed, self.distributed.net),
                         train_iterator)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_iterator, wrapper: ParallelWrapper = None,
                 **wrapper_kwargs):
        if wrapper is None:
            wrapper = ParallelWrapper(net, **wrapper_kwargs)
        self.wrapper = wrapper
        super().__init__(config, _FitFacade(wrapper, wrapper.net),
                         train_iterator)
