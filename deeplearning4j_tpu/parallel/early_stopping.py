"""Early stopping over multi-chip training.

Reference: `deeplearning4j-scaleout-parallelwrapper/.../
EarlyStoppingParallelTrainer.java` — the early-stopping epoch loop where
each epoch's fit runs through ParallelWrapper instead of single-device
`net.fit`.
"""
from __future__ import annotations

from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.result import EarlyStoppingResult
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


class _ParallelFitFacade:
    """Presents the (net + ParallelWrapper) pair as a single model whose
    `fit` is the sharded multi-chip step; everything else (score, listeners,
    serialization) proxies to the underlying network."""

    def __init__(self, wrapper: ParallelWrapper):
        object.__setattr__(self, "_wrapper", wrapper)

    def fit(self, iterator, epochs: int = 1):
        self._wrapper.fit(iterator, epochs=epochs)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_wrapper").net, name)

    def __setattr__(self, name, value):
        setattr(object.__getattribute__(self, "_wrapper").net, name, value)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_iterator, wrapper: ParallelWrapper = None,
                 **wrapper_kwargs):
        if wrapper is None:
            wrapper = ParallelWrapper(net, **wrapper_kwargs)
        self.wrapper = wrapper
        super().__init__(config, _ParallelFitFacade(wrapper), train_iterator)

    def fit(self) -> EarlyStoppingResult:
        result = super().fit()
        # unwrap the facade so callers get real networks back
        if result.best_model is not None and isinstance(
                result.best_model, _ParallelFitFacade):
            result.best_model = result.best_model._wrapper.net
        return result
