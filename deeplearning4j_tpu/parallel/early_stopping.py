"""Early stopping over multi-chip training.

Reference: `deeplearning4j-scaleout-parallelwrapper/.../
EarlyStoppingParallelTrainer.java` — the early-stopping epoch loop where
each epoch's fit runs through ParallelWrapper instead of single-device
`net.fit` — and `spark/earlystopping/SparkEarlyStoppingTrainer.java`,
the same loop driving the TrainingMaster's worker/averaging path.
"""
from __future__ import annotations

from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


class _FitFacade:
    """Presents a (fit delegate + net) pair as a single model: `fit` runs
    the delegate's multi-device path (ParallelWrapper sharded step, or the
    TrainingMaster's worker pool); everything else (score, listeners,
    serialization, clone) proxies to the underlying network — so model
    savers store real network clones, never the facade."""

    def __init__(self, fit_target, net):
        object.__setattr__(self, "_fit_target", fit_target)
        object.__setattr__(self, "_net", net)

    def fit(self, iterator, epochs: int = 1):
        object.__getattribute__(self, "_fit_target").fit(iterator,
                                                         epochs=epochs)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_net"), name)

    def __setattr__(self, name, value):
        setattr(object.__getattribute__(self, "_net"), name, value)


class _RecoveringFit:
    """Routes the facade's per-epoch fit through a `FaultTolerantTrainer`
    so each distributed epoch gets checkpoint-restore-retry semantics."""

    def __init__(self, fault_tolerant):
        self.fault_tolerant = fault_tolerant

    def fit(self, iterator, epochs: int = 1):
        self.fault_tolerant.fit(epochs=epochs, iterator=iterator)


class EarlyStoppingDistributedTrainer(EarlyStoppingTrainer):
    """Early stopping where each epoch's fit goes through the
    TrainingMaster's worker/averaging path (reference
    `spark/earlystopping/SparkEarlyStoppingTrainer.java` — extends
    `BaseSparkEarlyStoppingTrainer.fit`: per-epoch
    `trainingMaster.executeTraining`, then score calculators / termination
    conditions on the synced net). Iteration-level termination conditions
    fire through the master's `iteration_done` listener fan-out, exactly
    as on the single-device trainer.

    `checkpoint_dir` (optional) makes each epoch's distributed fit
    restart-aware: a `FaultTolerantTrainer` checkpoints every
    `checkpoint_every` iterations and, on a worker-tier failure that
    escapes the master's own retry/degradation layer, restores the newest
    VERIFIED checkpoint (durable atomic saves + integrity manifests via
    `util/checkpoint_store`) and resumes — up to `max_restarts` times
    (restart counts land in the master's `TrainingStats` when it collects
    stats). `checkpoint_save_hooks` passes chaos hooks
    (`CheckpointCrashInjector`) down to the store's save protocol."""

    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_iterator, training_master,
                 checkpoint_dir=None, checkpoint_every: int = 100,
                 max_restarts: int = 3, checkpoint_save_hooks=()):
        from deeplearning4j_tpu.parallel.training_master import (
            DistributedMultiLayer,
        )

        if isinstance(training_master, DistributedMultiLayer):
            if net is not None and training_master.net is not net:
                raise ValueError(
                    "EarlyStoppingDistributedTrainer was given BOTH an "
                    "existing DistributedMultiLayer and a different net — "
                    "the handle would silently train its own net, not the "
                    "one passed. Pass net=None or the handle's own net.")
            self.distributed = training_master
        else:
            self.distributed = DistributedMultiLayer(net, training_master)
        self.fault_tolerant = None
        fit_target = self.distributed
        if checkpoint_dir is not None:
            from deeplearning4j_tpu.earlystopping.trainer import (
                _IterationAbort,
            )
            from deeplearning4j_tpu.parallel.fault_tolerance import (
                FaultTolerantTrainer,
            )

            self.fault_tolerant = FaultTolerantTrainer(
                self.distributed, train_iterator,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                max_restarts=max_restarts,
                save_hooks=checkpoint_save_hooks,
                # iteration-condition aborts are control flow, not faults
                propagate=(_IterationAbort,))
            fit_target = _RecoveringFit(self.fault_tolerant)
        super().__init__(config,
                         _FitFacade(fit_target, self.distributed.net),
                         train_iterator)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_iterator, wrapper: ParallelWrapper = None,
                 **wrapper_kwargs):
        if wrapper is None:
            wrapper = ParallelWrapper(net, **wrapper_kwargs)
        self.wrapper = wrapper
        super().__init__(config, _FitFacade(wrapper, wrapper.net),
                         train_iterator)
