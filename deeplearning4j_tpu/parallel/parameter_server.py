"""Asynchronous data-parallel training via an in-process parameter server.

Reference: `deeplearning4j-scaleout-parallelwrapper-parameter-server/...
/ParameterServerParallelWrapper.java:39` — embeds an Aeron `MediaDriver`
(:160), starts a `ParameterServerNode` plus one `ParameterServerClient` per
worker (:215-218); workers asynchronously push gradients / pull parameters
over UDP.

TPU-native redesign: the Aeron UDP transport served cross-device traffic the
reference had no collective for. On TPU, synchronous ICI all-reduce
(`ParallelWrapper`) is strictly better *within* a pod, so the async PS is
kept for the role where asynchrony actually pays: loosely-coupled replicas
without a shared interconnect (multi-pod over DCN, preemptible fleets). The
server here is an in-process object with a lock (the `local[N]`-style
harness); the push/pull contract matches the reference's client API so a
networked transport can slot in behind it.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, List, Optional, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator,
    ListDataSetIterator,
)

logger = logging.getLogger("deeplearning4j_tpu")


class ParameterServer:
    """Shared parameter store with delta aggregation (reference: external
    `nd4j-parameter-server-node` — push gradient / pull params)."""

    _SEEN_PUSH_IDS_MAX = 1024

    def __init__(self, initial_params: np.ndarray):
        self._params = np.array(initial_params, copy=True)
        self._lock = threading.Lock()
        self._pushes = 0
        from collections import OrderedDict

        self._seen_push_ids: "OrderedDict[str, None]" = OrderedDict()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self._params.copy()

    def push_update(self, delta: np.ndarray,
                    request_id: Optional[str] = None) -> None:
        """Apply a worker's accumulated parameter delta (async, hogwild-ish:
        no barrier, last-writer ordering is whatever the scheduler does —
        same semantics as the reference's async PS).

        `request_id` makes the push IDEMPOTENT: a retry re-delivering the
        same logical push (its first attempt timed out but eventually
        committed anyway) is dropped instead of double-applying the delta.
        The dedup window keeps the most recent ids, bounded in memory."""
        with self._lock:
            if request_id is not None:
                if request_id in self._seen_push_ids:
                    logger.warning("parameter server: dropped duplicate "
                                   "push %s", request_id)
                    return
                self._seen_push_ids[request_id] = None
                while len(self._seen_push_ids) > self._SEEN_PUSH_IDS_MAX:
                    self._seen_push_ids.popitem(last=False)
            self._params += delta
            self._pushes += 1

    @property
    def num_pushes(self) -> int:
        with self._lock:
            return self._pushes


class ParameterServerTimeoutError(RuntimeError):
    """A parameter-server request kept timing out across bounded
    exponential-backoff retries — raised instead of deadlocking the
    worker on a stalled server."""


class _RequestDispatcher:
    """Single reusable daemon thread serving a client's store requests.
    When a request exceeds its timeout the dispatcher is marked abandoned
    and replaced (the stuck thread unwinds on its own once the store
    unblocks, then exits) — the healthy path reuses one thread instead of
    spawning one per pull/push."""

    def __init__(self):
        self.requests: "queue.Queue" = queue.Queue()
        self.abandoned = False
        threading.Thread(target=self._loop, daemon=True,
                         name="ps-client-dispatch").start()

    def submit(self, fn: Callable) -> "queue.Queue":
        box: "queue.Queue" = queue.Queue(maxsize=1)
        self.requests.put((fn, box))
        return box

    def _loop(self) -> None:
        while True:
            fn, box = self.requests.get()
            if fn is None:
                return
            try:
                box.put(("ok", fn()))
            except BaseException as e:  # noqa: BLE001 — ferried to caller
                box.put(("err", e))
            if self.abandoned:
                return

    def close(self) -> None:
        self.requests.put((None, None))


class RetryingParameterServerClient:
    """Timeout/retry decorator for ANY pull/push parameter-server store —
    the in-process `ParameterServer`, a `RemoteParameterServerClient`, or
    a chaos wrapper (`ParameterServerStallInjector`).

    Each request is served by a reusable dispatcher thread and must
    answer within `timeout` seconds; a late/stalled attempt is abandoned
    and retried after exponential backoff
    (`backoff × backoff_multiplier^attempt`), at most `max_retries`
    retries. Exhaustion raises `ParameterServerTimeoutError` — a stalled
    server can cost bounded wall-clock, never a deadlocked training run.
    `ConnectionError`/`OSError` (transport hiccups, e.g. a socket timeout
    from a remote client) retry under the same budget; other exceptions
    are re-raised immediately (they are bugs, not stalls).

    Retried pushes carry a per-logical-push `request_id` when the store's
    `push_update` accepts one (all stores in this module do), so an
    abandoned attempt that eventually commits anyway cannot double-apply
    the delta — retries are exactly-once against such stores, and
    at-least-once against foreign stores without dedup support.

    One client serves ONE calling thread (the reference wires a
    `ParameterServerClient` per worker for the same reason): concurrent
    callers would serialize on the single dispatcher and count each
    other's queue time against their own timeout. Give each worker its
    own client over the shared store, as
    `ParameterServerParallelWrapper` does."""

    def __init__(self, store, timeout: float = 5.0, max_retries: int = 3,
                 backoff: float = 0.05, backoff_multiplier: float = 2.0):
        if timeout <= 0:
            raise ValueError("timeout must be positive")
        self._store = store
        self.timeout = timeout
        self.max_retries = max_retries
        self.backoff = backoff
        self.backoff_multiplier = backoff_multiplier
        self.attempts = 0   # total request attempts (observability)
        self.timeouts = 0   # attempts that timed out / errored transiently
        self._dispatcher: Optional[_RequestDispatcher] = None
        import inspect

        try:
            params = inspect.signature(store.push_update).parameters
            self._push_idempotent = (
                "request_id" in params
                or any(p.kind is p.VAR_KEYWORD for p in params.values()))
        except (TypeError, ValueError):
            self._push_idempotent = False

    def _call(self, name: str, fn: Callable):
        delay = self.backoff
        last: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            self.attempts += 1
            d = self._dispatcher
            if d is None or d.abandoned:
                d = self._dispatcher = _RequestDispatcher()
            box = d.submit(fn)
            try:
                kind, val = box.get(timeout=self.timeout)
            except queue.Empty:
                d.abandoned = True
                self._dispatcher = None
                self.timeouts += 1
                last = ParameterServerTimeoutError(
                    f"parameter-server {name} timed out after "
                    f"{self.timeout}s (attempt {attempt + 1}/"
                    f"{self.max_retries + 1})")
                logger.warning("%s; backing off %.3fs", last, delay)
            else:
                if kind == "ok":
                    return val
                if not isinstance(val, (ConnectionError, OSError)):
                    raise val
                self.timeouts += 1
                last = val
                logger.warning(
                    "parameter-server %s failed (%s: %s); backing off "
                    "%.3fs (attempt %d/%d)", name, type(val).__name__, val,
                    delay, attempt + 1, self.max_retries + 1)
            if attempt < self.max_retries:
                time.sleep(delay)
                delay *= self.backoff_multiplier
        raise ParameterServerTimeoutError(
            f"parameter-server {name} gave up after "
            f"{self.max_retries + 1} attempts (last: {last})") from last

    def pull(self) -> np.ndarray:
        return self._call("pull", self._store.pull)

    def push_update(self, delta: np.ndarray) -> None:
        if self._push_idempotent:
            import uuid

            rid = uuid.uuid4().hex
            self._call("push", lambda: self._store.push_update(
                delta, request_id=rid))
        else:
            self._call("push", lambda: self._store.push_update(delta))

    @property
    def num_pushes(self) -> int:
        return self._store.num_pushes

    def shutdown(self) -> None:
        """Stop the dispatcher thread WITHOUT closing the wrapped store —
        the teardown for per-worker clients sharing one store."""
        if self._dispatcher is not None and not self._dispatcher.abandoned:
            self._dispatcher.close()
        self._dispatcher = None

    def close(self) -> None:
        self.shutdown()
        closer = getattr(self._store, "close", None)
        if closer is not None:
            closer()


def run_worker_protocol(store, replica, batches, sync_frequency: int) -> None:
    """THE worker half of the PS contract — pull, fit `sync_frequency`
    minibatches locally, push (new - pulled) as a delta, flush the tail.
    One definition shared by the in-process wrapper threads and both
    OS-process CLI modes, so the transport-parity test compares transports
    and can never drift on protocol details (sync cadence, tail flush)."""
    pending = 0
    pulled: Optional[np.ndarray] = None
    for ds in batches:
        if pending == 0:
            pulled = store.pull()
            replica.set_params(pulled)
        replica.fit(ds)
        pending += 1
        if pending >= sync_frequency:
            store.push_update(replica.params() - pulled)
            pending = 0
    if pending and pulled is not None:
        store.push_update(replica.params() - pulled)


class ParameterServerParallelWrapper:
    """Async multi-worker trainer (reference
    `ParameterServerParallelWrapper.java`).

    Each worker thread owns a model replica; it pulls current params, fits
    `sync_frequency` minibatches locally, then pushes (new - pulled) as a
    delta. Batches are distributed round-robin via a bounded queue (the
    reference uses `MagicQueue`-style per-worker queues).
    """

    _STOP = object()

    def __init__(self, net, workers: int = 2, sync_frequency: int = 1,
                 queue_capacity: int = 8, server=None,
                 request_timeout: Optional[float] = None,
                 max_retries: int = 3, retry_backoff: float = 0.05):
        """`server`: any object with the ParameterServer pull/push contract
        — pass a `RemoteParameterServerClient` to train against a
        `NetworkParameterServer` in another process/host (the reference's
        `ParameterServerClient`-per-worker wiring,
        `ParameterServerParallelWrapper.java:215-218`). Default: a fresh
        in-process store seeded from the net.

        `request_timeout`: when set, every worker pull/push goes through a
        `RetryingParameterServerClient` with this per-request timeout and
        `max_retries`/`retry_backoff` exponential backoff — a stalled
        server makes the run RAISE `ParameterServerTimeoutError` after
        bounded wall-clock instead of deadlocking the worker threads."""
        if workers < 1:
            raise ValueError("workers must be >= 1")
        net._ensure_init()
        self.net = net
        self.workers = workers
        self.sync_frequency = max(1, sync_frequency)
        self._queues: List[queue.Queue] = [
            queue.Queue(maxsize=queue_capacity) for _ in range(workers)]
        self.server = (ParameterServer(net.params()) if server is None
                       else server)
        self._retry_conf = (request_timeout, max_retries, retry_backoff)
        # the master's own client (final pull); each worker thread builds
        # its own in _worker_loop — a RetryingParameterServerClient serves
        # one thread (see its docstring)
        self._client = self._make_client()
        self._worker_errors: List[BaseException] = []
        self._threads: List[threading.Thread] = []

    def _make_client(self):
        request_timeout, max_retries, retry_backoff = self._retry_conf
        if request_timeout is None:
            return self.server
        return RetryingParameterServerClient(
            self.server, timeout=request_timeout,
            max_retries=max_retries, backoff=retry_backoff)

    def _check_worker_failure(self) -> None:
        if self._worker_errors:
            # re-raise the worker's own exception (e.g.
            # ParameterServerTimeoutError) so callers handle the real cause
            raise self._worker_errors[0]

    def _dispatch(self, ds, idx: int) -> None:
        """Bounded put that never blocks forever on a dead consumer: if
        the target worker thread died (e.g. its PS client gave up), its
        error surfaces here instead of wedging fit() on a full queue."""
        q = self._queues[idx]
        while True:
            try:
                q.put(ds, timeout=0.2)
                return
            except queue.Full:
                if not self._threads[idx].is_alive():
                    self._check_worker_failure()
                    raise RuntimeError(
                        f"ps-worker-{idx} died without draining its queue")

    def fit(self, data: Union[DataSet, DataSetIterator],
            epochs: int = 1) -> None:
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])

        self._worker_errors = []
        self._threads = [
            threading.Thread(target=self._worker_loop, args=(w,),
                             daemon=True, name=f"ps-worker-{w}")
            for w in range(self.workers)]
        for t in self._threads:
            t.start()
        n_batches = 0
        try:
            for _ in range(epochs):
                data.reset()
                for ds in data:
                    self._dispatch(ds, n_batches % self.workers)
                    n_batches += 1
        finally:
            for w, q in enumerate(self._queues):
                while True:  # deliver STOP unless the consumer is gone
                    try:
                        q.put(self._STOP, timeout=0.2)
                        break
                    except queue.Full:
                        if not self._threads[w].is_alive():
                            break
            for t in self._threads:
                t.join()
        self._check_worker_failure()
        # final model = server state (reference copies PS params back)
        self.net.set_params(self._client.pull())
        self.net.iteration += n_batches
        logger.info("parameter server: %d batches, %d pushes",
                    n_batches, self.server.num_pushes)

    def _worker_loop(self, idx: int) -> None:
        client = None
        try:
            client = self._make_client()  # per-worker (reference wiring)
            replica = self.net.clone()
            q = self._queues[idx]

            def batches():
                while True:
                    item = q.get()
                    if item is self._STOP:
                        return
                    yield item

            run_worker_protocol(client, replica, batches(),
                                self.sync_frequency)
            # propagate the last score for listener/reporting purposes
            if replica.score_value is not None:
                self.net.score_value = replica.score_value
        except BaseException as e:  # noqa: BLE001 — surfaced by fit()
            logger.warning("ps-worker-%d died: %s: %s", idx,
                           type(e).__name__, e)
            self._worker_errors.append(e)
        finally:
            # dispatcher-only shutdown: the wrapped store is SHARED
            if isinstance(client, RetryingParameterServerClient):
                client.shutdown()


# ---------------------------------------------------------------------------
# Network transport (the Aeron role)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("parameter-server peer closed")
        buf += chunk
    return buf


def _send_msg(sock, op: bytes, payload: bytes = b"") -> None:
    import struct

    sock.sendall(op + struct.pack(">Q", len(payload)) + payload)


def _recv_msg(sock):
    import struct

    op = _recv_exact(sock, 1)
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    return op, _recv_exact(sock, n)


class NetworkParameterServer:
    """TCP-served `ParameterServer` (the role of the reference's embedded
    Aeron `MediaDriver` + `ParameterServerNode`,
    `ParameterServerParallelWrapper.java:160-218`). Aeron is reliable
    UDP; a plain TCP stream gives the same reliable push/pull contract
    without vendoring a media driver, and the protocol (1-byte opcode +
    length-prefixed f32 payload) keeps the wire format trivial for a
    faster transport to replace.

    Serves PULL (current params) and PUSH (delta accumulate) from any
    number of clients/processes/hosts; one handler thread per client."""

    def __init__(self, initial_params: np.ndarray, host: str = "localhost",
                 port: int = 0):
        import socket

        self._store = ParameterServer(initial_params)
        self._dtype = np.float32
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.address = self._sock.getsockname()  # (host, port)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True,
                                               name="ps-accept")
        self._accept_thread.start()

    # store passthroughs (the server process reads its own aggregate)
    def pull(self) -> np.ndarray:
        return self._store.pull()

    @property
    def num_pushes(self) -> int:
        return self._store.num_pushes

    def _accept_loop(self) -> None:
        import socket

        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="ps-conn")
            t.start()
            self._threads.append(t)

    def _serve(self, conn) -> None:
        try:
            while True:
                op, payload = _recv_msg(conn)
                if op == b"P":                      # pull
                    params = self._store.pull().astype(self._dtype)
                    _send_msg(conn, b"R", params.tobytes())
                elif op == b"U":                    # push delta
                    delta = np.frombuffer(payload, self._dtype)
                    self._store.push_update(delta.astype(np.float64)
                                            .astype(self._dtype))
                    _send_msg(conn, b"A")           # ack: delta applied
                elif op == b"V":                    # idempotent push:
                    rid = payload[:32].decode()     # 32-byte hex id + delta
                    delta = np.frombuffer(payload[32:], self._dtype)
                    self._store.push_update(delta.astype(np.float64)
                                            .astype(self._dtype),
                                            request_id=rid)
                    _send_msg(conn, b"A")
                elif op == b"Q":
                    return
                else:
                    raise ValueError(f"unknown parameter-server op {op!r}")
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteParameterServerClient:
    """Client with the SAME pull/push contract as the in-process
    `ParameterServer` (reference `ParameterServerClient`) — so
    `ParameterServerParallelWrapper(server=...)` and any external process
    can train against a networked server. Push is synchronous through the
    ack (reliable delivery, matching Aeron's reliable-stream semantics);
    asynchrony lives in the training protocol (no barrier between
    workers), not in dropped updates.

    `timeout`: per-socket-operation timeout in seconds — a stalled or
    dead server raises `socket.timeout` (an OSError) instead of blocking
    recv forever; wrap in `RetryingParameterServerClient` for bounded
    backoff-and-retry on top. Any socket error (including a timeout)
    DISCARDS the connection — the length-prefixed stream may hold a
    half-consumed reply, so the next request transparently reconnects on
    a clean stream instead of desyncing the protocol. A mis-sequenced
    reply on a supposedly-clean stream raises ConnectionError (also
    retryable) rather than poisoning every later request."""

    def __init__(self, host: str, port: int,
                 timeout: Optional[float] = None):
        self._host = host
        self._port = port
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock = None
        with self._lock:
            self._connect()

    def _connect(self) -> None:
        import socket

        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        if self._timeout is not None:
            sock.settimeout(self._timeout)
        sock.connect((self._host, self._port))
        self._sock = sock

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _request(self, op: bytes, payload: bytes, expect: bytes):
        with self._lock:
            try:
                if self._sock is None:
                    self._connect()
                _send_msg(self._sock, op, payload)
                reply_op, reply = _recv_msg(self._sock)
            except (ConnectionError, OSError):
                self._drop_sock()
                raise
            if reply_op != expect:
                # protocol desync (e.g. a reply from a request abandoned
                # before the reconnect logic existed server-side) — start
                # over on a fresh stream and let the retry layer re-call
                self._drop_sock()
                raise ConnectionError(
                    f"unexpected parameter-server reply {reply_op!r} "
                    f"(expected {expect!r}); reconnecting")
            return reply

    def pull(self) -> np.ndarray:
        payload = self._request(b"P", b"", expect=b"R")
        return np.frombuffer(payload, np.float32).copy()

    def push_update(self, delta: np.ndarray,
                    request_id: Optional[str] = None) -> None:
        """`request_id` (32-char hex): server-side duplicate suppression
        for retried pushes (see `ParameterServer.push_update`)."""
        payload = np.asarray(delta, np.float32).tobytes()
        if request_id is None:
            self._request(b"U", payload, expect=b"A")
        else:
            self._request(b"V", request_id.encode()[:32].ljust(32) + payload,
                          expect=b"A")

    @property
    def num_pushes(self) -> int:  # server-side stat; clients don't track
        return -1

    def close(self) -> None:
        with self._lock:
            try:
                if self._sock is not None:
                    _send_msg(self._sock, b"Q")
            except OSError:
                pass
            self._drop_sock()


# ---------------------------------------------------------------------------
# OS-process worker entry (test/dryrun rig for the network transport)


def _network_worker_main() -> None:
    """Train the shared parity fixture against a NetworkParameterServer in
    ANOTHER process: `python -m deeplearning4j_tpu.parallel.parameter_server
    <host> <port> <worker_id> <n_workers> <sync_frequency> <mode>`.

    mode 'train': pull -> fit this worker's slice of the fixture stream
    (round-robin, the wrapper's dispatch order) -> push deltas every
    `sync_frequency` batches — the real worker protocol over TCP.
    mode 'hammer': push 50 constant 0.5-deltas (exactly representable, so
    the aggregate under CONCURRENT pushes has one correct answer — proves
    the per-connection handler threads don't drop or double-apply).
    mode 'local': no network at all — run EVERY worker's sequence against
    an in-process ParameterServer and save the final params to the path
    in argv[7]; the parity test diffs this against the TCP result from an
    identically-configured interpreter, isolating the transport."""
    import os
    import sys

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    host, port, wid, n_workers, sync_freq, mode = sys.argv[1:7]
    port, wid = int(port), int(wid)
    n_workers, sync_freq = int(n_workers), int(sync_freq)

    if mode == "local":
        from deeplearning4j_tpu.parallel.multiprocess import (
            _parity_fixture_data,
            _parity_fixture_net,
        )

        # argv[8] (optional): EXACT initial params of the server being
        # compared against — re-deriving them from the fixture net here
        # would differ by ~1 ulp across interpreter configs (x64 flag,
        # platform) and diverge the whole trajectory
        init = (np.load(sys.argv[8]) if len(sys.argv) > 8
                else _parity_fixture_net().params())
        store = ParameterServer(init)
        feats, labels = _parity_fixture_data()
        for w in range(n_workers):
            run_worker_protocol(
                store, _parity_fixture_net(),
                [DataSet(feats[i], labels[i])
                 for i in range(feats.shape[0]) if i % n_workers == w],
                sync_freq)
        np.save(sys.argv[7], store.pull())
        print("PS_LOCAL_REF_DONE")
        return

    client = RemoteParameterServerClient(host, port)
    if mode == "hammer":
        import jax  # noqa: F401  (mirror train-mode import cost)

        size = len(client.pull())
        for _ in range(50):
            client.push_update(np.full((size,), 0.5, np.float32))
        client.close()
        print(f"PS_WORKER_{wid}_DONE hammer")
        return

    from deeplearning4j_tpu.parallel.multiprocess import (
        _parity_fixture_data,
        _parity_fixture_net,
    )

    net = _parity_fixture_net()
    feats, labels = _parity_fixture_data()
    run_worker_protocol(
        client, net,
        [DataSet(feats[i], labels[i])
         for i in range(feats.shape[0]) if i % n_workers == wid],
        sync_freq)
    client.close()
    print(f"PS_WORKER_{wid}_DONE train score={net.score_value:.6f}")


if __name__ == "__main__":
    _network_worker_main()
