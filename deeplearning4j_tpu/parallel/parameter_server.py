"""Asynchronous data-parallel training via an in-process parameter server.

Reference: `deeplearning4j-scaleout-parallelwrapper-parameter-server/...
/ParameterServerParallelWrapper.java:39` — embeds an Aeron `MediaDriver`
(:160), starts a `ParameterServerNode` plus one `ParameterServerClient` per
worker (:215-218); workers asynchronously push gradients / pull parameters
over UDP.

TPU-native redesign: the Aeron UDP transport served cross-device traffic the
reference had no collective for. On TPU, synchronous ICI all-reduce
(`ParallelWrapper`) is strictly better *within* a pod, so the async PS is
kept for the role where asynchrony actually pays: loosely-coupled replicas
without a shared interconnect (multi-pod over DCN, preemptible fleets). The
server here is an in-process object with a lock (the `local[N]`-style
harness); the push/pull contract matches the reference's client API so a
networked transport can slot in behind it.
"""
from __future__ import annotations

import logging
import queue
import threading
from typing import List, Optional, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator,
    ListDataSetIterator,
)

logger = logging.getLogger("deeplearning4j_tpu")


class ParameterServer:
    """Shared parameter store with delta aggregation (reference: external
    `nd4j-parameter-server-node` — push gradient / pull params)."""

    def __init__(self, initial_params: np.ndarray):
        self._params = np.array(initial_params, copy=True)
        self._lock = threading.Lock()
        self._pushes = 0

    def pull(self) -> np.ndarray:
        with self._lock:
            return self._params.copy()

    def push_update(self, delta: np.ndarray) -> None:
        """Apply a worker's accumulated parameter delta (async, hogwild-ish:
        no barrier, last-writer ordering is whatever the scheduler does —
        same semantics as the reference's async PS)."""
        with self._lock:
            self._params += delta
            self._pushes += 1

    @property
    def num_pushes(self) -> int:
        with self._lock:
            return self._pushes


class ParameterServerParallelWrapper:
    """Async multi-worker trainer (reference
    `ParameterServerParallelWrapper.java`).

    Each worker thread owns a model replica; it pulls current params, fits
    `sync_frequency` minibatches locally, then pushes (new - pulled) as a
    delta. Batches are distributed round-robin via a bounded queue (the
    reference uses `MagicQueue`-style per-worker queues).
    """

    _STOP = object()

    def __init__(self, net, workers: int = 2, sync_frequency: int = 1,
                 queue_capacity: int = 8):
        if workers < 1:
            raise ValueError("workers must be >= 1")
        net._ensure_init()
        self.net = net
        self.workers = workers
        self.sync_frequency = max(1, sync_frequency)
        self._queues: List[queue.Queue] = [
            queue.Queue(maxsize=queue_capacity) for _ in range(workers)]
        self.server = ParameterServer(net.params())

    def fit(self, data: Union[DataSet, DataSetIterator],
            epochs: int = 1) -> None:
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])

        threads = [threading.Thread(target=self._worker_loop, args=(w,),
                                    daemon=True, name=f"ps-worker-{w}")
                   for w in range(self.workers)]
        for t in threads:
            t.start()
        n_batches = 0
        try:
            for _ in range(epochs):
                data.reset()
                for ds in data:
                    self._queues[n_batches % self.workers].put(ds)
                    n_batches += 1
        finally:
            for q in self._queues:
                q.put(self._STOP)
            for t in threads:
                t.join()
        # final model = server state (reference copies PS params back)
        self.net.set_params(self.server.pull())
        self.net.iteration += n_batches
        logger.info("parameter server: %d batches, %d pushes",
                    n_batches, self.server.num_pushes)

    def _worker_loop(self, idx: int) -> None:
        replica = self.net.clone()
        q = self._queues[idx]
        pending = 0
        pulled: Optional[np.ndarray] = None
        while True:
            item = q.get()
            if item is self._STOP:
                break
            if pending == 0:
                pulled = self.server.pull()
                replica.set_params(pulled)
            replica.fit(item)
            pending += 1
            if pending >= self.sync_frequency:
                self.server.push_update(replica.params() - pulled)
                pending = 0
        if pending and pulled is not None:
            self.server.push_update(replica.params() - pulled)
        # propagate the last score for listener/reporting purposes
        if replica.score_value is not None:
            self.net.score_value = replica.score_value
