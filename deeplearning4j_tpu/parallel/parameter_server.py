"""Asynchronous data-parallel training via an in-process parameter server.

Reference: `deeplearning4j-scaleout-parallelwrapper-parameter-server/...
/ParameterServerParallelWrapper.java:39` — embeds an Aeron `MediaDriver`
(:160), starts a `ParameterServerNode` plus one `ParameterServerClient` per
worker (:215-218); workers asynchronously push gradients / pull parameters
over UDP.

TPU-native redesign: the Aeron UDP transport served cross-device traffic the
reference had no collective for. On TPU, synchronous ICI all-reduce
(`ParallelWrapper`) is strictly better *within* a pod, so the async PS is
kept for the role where asynchrony actually pays: loosely-coupled replicas
without a shared interconnect (multi-pod over DCN, preemptible fleets). The
server here is an in-process object with a lock (the `local[N]`-style
harness); the push/pull contract matches the reference's client API so a
networked transport can slot in behind it.
"""
from __future__ import annotations

import logging
import queue
import threading
from typing import List, Optional, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator,
    ListDataSetIterator,
)

logger = logging.getLogger("deeplearning4j_tpu")


class ParameterServer:
    """Shared parameter store with delta aggregation (reference: external
    `nd4j-parameter-server-node` — push gradient / pull params)."""

    def __init__(self, initial_params: np.ndarray):
        self._params = np.array(initial_params, copy=True)
        self._lock = threading.Lock()
        self._pushes = 0

    def pull(self) -> np.ndarray:
        with self._lock:
            return self._params.copy()

    def push_update(self, delta: np.ndarray) -> None:
        """Apply a worker's accumulated parameter delta (async, hogwild-ish:
        no barrier, last-writer ordering is whatever the scheduler does —
        same semantics as the reference's async PS)."""
        with self._lock:
            self._params += delta
            self._pushes += 1

    @property
    def num_pushes(self) -> int:
        with self._lock:
            return self._pushes


def run_worker_protocol(store, replica, batches, sync_frequency: int) -> None:
    """THE worker half of the PS contract — pull, fit `sync_frequency`
    minibatches locally, push (new - pulled) as a delta, flush the tail.
    One definition shared by the in-process wrapper threads and both
    OS-process CLI modes, so the transport-parity test compares transports
    and can never drift on protocol details (sync cadence, tail flush)."""
    pending = 0
    pulled: Optional[np.ndarray] = None
    for ds in batches:
        if pending == 0:
            pulled = store.pull()
            replica.set_params(pulled)
        replica.fit(ds)
        pending += 1
        if pending >= sync_frequency:
            store.push_update(replica.params() - pulled)
            pending = 0
    if pending and pulled is not None:
        store.push_update(replica.params() - pulled)


class ParameterServerParallelWrapper:
    """Async multi-worker trainer (reference
    `ParameterServerParallelWrapper.java`).

    Each worker thread owns a model replica; it pulls current params, fits
    `sync_frequency` minibatches locally, then pushes (new - pulled) as a
    delta. Batches are distributed round-robin via a bounded queue (the
    reference uses `MagicQueue`-style per-worker queues).
    """

    _STOP = object()

    def __init__(self, net, workers: int = 2, sync_frequency: int = 1,
                 queue_capacity: int = 8, server=None):
        """`server`: any object with the ParameterServer pull/push contract
        — pass a `RemoteParameterServerClient` to train against a
        `NetworkParameterServer` in another process/host (the reference's
        `ParameterServerClient`-per-worker wiring,
        `ParameterServerParallelWrapper.java:215-218`). Default: a fresh
        in-process store seeded from the net."""
        if workers < 1:
            raise ValueError("workers must be >= 1")
        net._ensure_init()
        self.net = net
        self.workers = workers
        self.sync_frequency = max(1, sync_frequency)
        self._queues: List[queue.Queue] = [
            queue.Queue(maxsize=queue_capacity) for _ in range(workers)]
        self.server = (ParameterServer(net.params()) if server is None
                       else server)

    def fit(self, data: Union[DataSet, DataSetIterator],
            epochs: int = 1) -> None:
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])

        threads = [threading.Thread(target=self._worker_loop, args=(w,),
                                    daemon=True, name=f"ps-worker-{w}")
                   for w in range(self.workers)]
        for t in threads:
            t.start()
        n_batches = 0
        try:
            for _ in range(epochs):
                data.reset()
                for ds in data:
                    self._queues[n_batches % self.workers].put(ds)
                    n_batches += 1
        finally:
            for q in self._queues:
                q.put(self._STOP)
            for t in threads:
                t.join()
        # final model = server state (reference copies PS params back)
        self.net.set_params(self.server.pull())
        self.net.iteration += n_batches
        logger.info("parameter server: %d batches, %d pushes",
                    n_batches, self.server.num_pushes)

    def _worker_loop(self, idx: int) -> None:
        replica = self.net.clone()
        q = self._queues[idx]

        def batches():
            while True:
                item = q.get()
                if item is self._STOP:
                    return
                yield item

        run_worker_protocol(self.server, replica, batches(),
                            self.sync_frequency)
        # propagate the last score for listener/reporting purposes
        if replica.score_value is not None:
            self.net.score_value = replica.score_value


# ---------------------------------------------------------------------------
# Network transport (the Aeron role)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("parameter-server peer closed")
        buf += chunk
    return buf


def _send_msg(sock, op: bytes, payload: bytes = b"") -> None:
    import struct

    sock.sendall(op + struct.pack(">Q", len(payload)) + payload)


def _recv_msg(sock):
    import struct

    op = _recv_exact(sock, 1)
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    return op, _recv_exact(sock, n)


class NetworkParameterServer:
    """TCP-served `ParameterServer` (the role of the reference's embedded
    Aeron `MediaDriver` + `ParameterServerNode`,
    `ParameterServerParallelWrapper.java:160-218`). Aeron is reliable
    UDP; a plain TCP stream gives the same reliable push/pull contract
    without vendoring a media driver, and the protocol (1-byte opcode +
    length-prefixed f32 payload) keeps the wire format trivial for a
    faster transport to replace.

    Serves PULL (current params) and PUSH (delta accumulate) from any
    number of clients/processes/hosts; one handler thread per client."""

    def __init__(self, initial_params: np.ndarray, host: str = "localhost",
                 port: int = 0):
        import socket

        self._store = ParameterServer(initial_params)
        self._dtype = np.float32
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.address = self._sock.getsockname()  # (host, port)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True,
                                               name="ps-accept")
        self._accept_thread.start()

    # store passthroughs (the server process reads its own aggregate)
    def pull(self) -> np.ndarray:
        return self._store.pull()

    @property
    def num_pushes(self) -> int:
        return self._store.num_pushes

    def _accept_loop(self) -> None:
        import socket

        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="ps-conn")
            t.start()
            self._threads.append(t)

    def _serve(self, conn) -> None:
        try:
            while True:
                op, payload = _recv_msg(conn)
                if op == b"P":                      # pull
                    params = self._store.pull().astype(self._dtype)
                    _send_msg(conn, b"R", params.tobytes())
                elif op == b"U":                    # push delta
                    delta = np.frombuffer(payload, self._dtype)
                    self._store.push_update(delta.astype(np.float64)
                                            .astype(self._dtype))
                    _send_msg(conn, b"A")           # ack: delta applied
                elif op == b"Q":
                    return
                else:
                    raise ValueError(f"unknown parameter-server op {op!r}")
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteParameterServerClient:
    """Client with the SAME pull/push contract as the in-process
    `ParameterServer` (reference `ParameterServerClient`) — so
    `ParameterServerParallelWrapper(server=...)` and any external process
    can train against a networked server. Push is synchronous through the
    ack (reliable delivery, matching Aeron's reliable-stream semantics);
    asynchrony lives in the training protocol (no barrier between
    workers), not in dropped updates."""

    def __init__(self, host: str, port: int):
        import socket

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.connect((host, port))
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            _send_msg(self._sock, b"P")
            op, payload = _recv_msg(self._sock)
        if op != b"R":
            raise ValueError(f"unexpected parameter-server reply {op!r}")
        return np.frombuffer(payload, np.float32).copy()

    def push_update(self, delta: np.ndarray) -> None:
        with self._lock:
            _send_msg(self._sock, b"U",
                      np.asarray(delta, np.float32).tobytes())
            op, _ = _recv_msg(self._sock)
        if op != b"A":
            raise ValueError(f"push not acknowledged: {op!r}")

    @property
    def num_pushes(self) -> int:  # server-side stat; clients don't track
        return -1

    def close(self) -> None:
        try:
            _send_msg(self._sock, b"Q")
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# OS-process worker entry (test/dryrun rig for the network transport)


def _network_worker_main() -> None:
    """Train the shared parity fixture against a NetworkParameterServer in
    ANOTHER process: `python -m deeplearning4j_tpu.parallel.parameter_server
    <host> <port> <worker_id> <n_workers> <sync_frequency> <mode>`.

    mode 'train': pull -> fit this worker's slice of the fixture stream
    (round-robin, the wrapper's dispatch order) -> push deltas every
    `sync_frequency` batches — the real worker protocol over TCP.
    mode 'hammer': push 50 constant 0.5-deltas (exactly representable, so
    the aggregate under CONCURRENT pushes has one correct answer — proves
    the per-connection handler threads don't drop or double-apply).
    mode 'local': no network at all — run EVERY worker's sequence against
    an in-process ParameterServer and save the final params to the path
    in argv[7]; the parity test diffs this against the TCP result from an
    identically-configured interpreter, isolating the transport."""
    import os
    import sys

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    host, port, wid, n_workers, sync_freq, mode = sys.argv[1:7]
    port, wid = int(port), int(wid)
    n_workers, sync_freq = int(n_workers), int(sync_freq)

    if mode == "local":
        from deeplearning4j_tpu.parallel.multiprocess import (
            _parity_fixture_data,
            _parity_fixture_net,
        )

        # argv[8] (optional): EXACT initial params of the server being
        # compared against — re-deriving them from the fixture net here
        # would differ by ~1 ulp across interpreter configs (x64 flag,
        # platform) and diverge the whole trajectory
        init = (np.load(sys.argv[8]) if len(sys.argv) > 8
                else _parity_fixture_net().params())
        store = ParameterServer(init)
        feats, labels = _parity_fixture_data()
        for w in range(n_workers):
            run_worker_protocol(
                store, _parity_fixture_net(),
                [DataSet(feats[i], labels[i])
                 for i in range(feats.shape[0]) if i % n_workers == w],
                sync_freq)
        np.save(sys.argv[7], store.pull())
        print("PS_LOCAL_REF_DONE")
        return

    client = RemoteParameterServerClient(host, port)
    if mode == "hammer":
        import jax  # noqa: F401  (mirror train-mode import cost)

        size = len(client.pull())
        for _ in range(50):
            client.push_update(np.full((size,), 0.5, np.float32))
        client.close()
        print(f"PS_WORKER_{wid}_DONE hammer")
        return

    from deeplearning4j_tpu.parallel.multiprocess import (
        _parity_fixture_data,
        _parity_fixture_net,
    )

    net = _parity_fixture_net()
    feats, labels = _parity_fixture_data()
    run_worker_protocol(
        client, net,
        [DataSet(feats[i], labels[i])
         for i in range(feats.shape[0]) if i % n_workers == wid],
        sync_freq)
    client.close()
    print(f"PS_WORKER_{wid}_DONE train score={net.score_value:.6f}")


if __name__ == "__main__":
    _network_worker_main()
