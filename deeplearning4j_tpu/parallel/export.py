"""Export-staged distributed training: batch-and-export DataSets to
files, then train from paths.

Reference: the second RDD training approach
(`spark/api/RDDTrainingApproach.java` Export,
`spark/data/BatchAndExportDataSetsFunction.java`,
`ParameterAveragingTrainingMaster.executeTrainingPathsHelper`): instead of
holding the whole training set in executor memory, batches are re-batched
to a uniform minibatch size, written to files, and workers stream them
from paths — the larger-than-memory seam.

TPU-native shape: files are npz DataSets (`DataSet.save/load`),
`FileDataSetIterator` streams them one at a time, and
`ParameterAveragingTrainingMaster.execute_training_paths` drives the same
averaging schedule over the exported shards.
"""
from __future__ import annotations

import fnmatch
import os
from typing import List

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import IteratorDataSetIterator


def batch_and_export(iterator, export_dir, batch_size: int,
                     prefix: str = "dataset") -> List[str]:
    """Re-batch a stream of DataSets to a uniform `batch_size` and write
    one file per batch under `export_dir` (created if needed). Returns
    the ordered list of written paths.

    Matches `BatchAndExportDataSetsFunction.java`: incoming batches of any
    size are split/merged so every exported file except possibly the last
    holds exactly `batch_size` examples — uniform minibatches keep the
    compiled train step at ONE shape (one XLA executable). Re-batching
    (including mixed-mask merge semantics) is `IteratorDataSetIterator` —
    the exact batches a consumer would see training in-memory.

    Stale shards from a previous export under the same prefix are removed
    first: directory-mode `FileDataSetIterator(export_dir)` globs every
    npz, and a smaller re-export would otherwise silently train on
    leftover files from the earlier run."""
    export_dir = os.fspath(export_dir)
    os.makedirs(export_dir, exist_ok=True)
    for f in os.listdir(export_dir):
        if fnmatch.fnmatch(f, f"{prefix}_*.npz"):
            os.remove(os.path.join(export_dir, f))
    paths: List[str] = []
    rebatch = IteratorDataSetIterator(iterator, batch_size)
    while rebatch.has_next():
        path = os.path.join(export_dir, f"{prefix}_{len(paths):06d}.npz")
        rebatch.next().save(path)
        paths.append(path)
    return paths
