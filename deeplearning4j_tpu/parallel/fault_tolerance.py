"""Checkpoint-based fault tolerance + fault-injection framework.

Reference (SURVEY §5 "Failure detection / elastic recovery"): absent — the
reference inherits Spark task retry and nothing else; there is no
checkpoint-based elasticity and no fault-injection framework. Both are
table stakes for long TPU runs (preemptible pods), so this build provides:

- `FaultTolerantTrainer`: drives `fit` epoch-by-epoch with periodic
  checkpoints; on a transient failure it restores the newest checkpoint
  (model + updater state + iteration clock) and resumes, up to
  `max_restarts` times. Works on a bare network AND on distributed
  handles (`DistributedMultiLayer`, `ParallelWrapper`) — anything with a
  `fit(iterator, epochs=)` whose underlying network is reachable via
  `get_network()`.
- Fault injectors, all logging through the `deeplearning4j_tpu` logger so
  chaos tests assert on `caplog` rather than stdout:
  * `FaultInjectionListener` — single-node: raise at iteration N.
  * `WorkerCrashInjector` — distributed: worker k raises on its n-th fit.
  * `SlowWorkerInjector` — distributed: worker k sleeps per minibatch,
    exercising the master's straggler `worker_timeout`.
  * `ParameterServerStallInjector` — wraps a parameter-server store so
    push/pull block, exercising the client's timeout/backoff give-up.
  * `CheckpointCrashInjector` — kills a checkpoint SAVE at a chosen
    phase (mid-write, pre-publish, between payload and manifest),
    exercising the durable store's atomic-commit + last-good-fallback
    guarantees (`util/checkpoint_store.py`).
  * `NaNGradientInjector` — poisons a minibatch's features with NaN/Inf
    so loss/gradients go non-finite at a chosen step, TRANSIENTLY (the
    original data is restored/untouched) — exercises the health
    sentinel's fused skip guard and escalation ladder
    (`optimize/health.py`).
  * `PoisonBatchInjector` — poisons specific records PERSISTENTLY (every
    replay/re-dispatch sees the same bad record) — exercises quarantine
    and the exhausted-budget `TrainingDivergedError` path.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from deeplearning4j_tpu.optimize.listeners import (
    CheckpointListener,
    IterationListener,
)
from deeplearning4j_tpu.parallel.training_master import (
    TrainingHook,
    current_worker_id,
)

logger = logging.getLogger("deeplearning4j_tpu")


class InjectedFault(RuntimeError):
    """Raised by fault injectors (distinguishable from real bugs)."""


class FaultInjectionListener(IterationListener):
    """Raises `InjectedFault` once training reaches `fail_at_iteration`
    (>=, so a restarted run that resumes past the trigger still fires);
    fires at most `times` times."""

    def __init__(self, fail_at_iteration: int, times: int = 1):
        self.fail_at_iteration = fail_at_iteration
        self.remaining = times
        self.fired = 0

    def iteration_done(self, model, iteration: int) -> None:
        if self.remaining > 0 and iteration >= self.fail_at_iteration:
            self.remaining -= 1
            self.fired += 1
            logger.warning("FaultInjectionListener: injected fault at "
                           "iteration %d", iteration)
            raise InjectedFault(
                f"injected fault at iteration {iteration}")


# ---------------------------------------------------------------------------
# distributed injectors (TrainingHook seam — attach via
# `ParameterAveragingTrainingWorker.add_hook`)


class WorkerCrashInjector(TrainingHook):
    """TrainingHook: worker `worker_id` raises `InjectedFault` in
    `pre_update` once it has seen `fail_at_fit` minibatches (1-based,
    counted across shards and retries), at most `times` times.
    Thread-safe: hooks fire concurrently from shard threads."""

    def __init__(self, worker_id: int, fail_at_fit: int = 1,
                 times: int = 1):
        self.worker_id = worker_id
        self.fail_at_fit = fail_at_fit
        self.remaining = times
        self.fired = 0
        self._fits = 0
        self._lock = threading.Lock()

    def pre_update(self, ds, net) -> None:
        if current_worker_id() != self.worker_id:
            return
        with self._lock:
            self._fits += 1
            if self._fits < self.fail_at_fit or self.remaining <= 0:
                return
            self.remaining -= 1
            self.fired += 1
            fits = self._fits
        logger.warning("WorkerCrashInjector: injected crash on worker %d "
                       "(fit %d)", self.worker_id, fits)
        raise InjectedFault(
            f"injected crash on worker {self.worker_id} (fit {fits})")


class SlowWorkerInjector(TrainingHook):
    """TrainingHook: worker `worker_id` sleeps `delay` seconds before each
    of its first `times` minibatches — a deterministic straggler to
    exercise the master's `worker_timeout` path. Keep `delay` bounded in
    tests: the hung shard thread runs to completion in the background (its
    result is discarded), and an unbounded sleep would outlive the test."""

    def __init__(self, worker_id: int, delay: float, times: int = 1):
        self.worker_id = worker_id
        self.delay = delay
        self.remaining = times
        self.fired = 0
        self._lock = threading.Lock()

    def pre_update(self, ds, net) -> None:
        if current_worker_id() != self.worker_id:
            return
        with self._lock:
            if self.remaining <= 0:
                return
            self.remaining -= 1
            self.fired += 1
        logger.warning("SlowWorkerInjector: delaying worker %d by %.2fs",
                       self.worker_id, self.delay)
        time.sleep(self.delay)


class ParameterServerStallInjector:
    """Wraps any pull/push parameter-server store; after `stall_after`
    successful requests, every request blocks for `stall_seconds` (or
    until `release()`) before reaching the store — the PS-stall chaos
    hook. Pair with `RetryingParameterServerClient` to prove a stalled
    server raises after bounded backoff instead of deadlocking."""

    def __init__(self, store, stall_after: int = 0,
                 stall_seconds: float = 3600.0):
        self._store = store
        self.stall_after = stall_after
        self.stall_seconds = stall_seconds
        self.requests = 0
        self.stalled_requests = 0
        self._released = threading.Event()
        self._lock = threading.Lock()

    def release(self) -> None:
        """Un-stall (lets background threads stuck in a stalled request
        finish promptly at test teardown)."""
        self._released.set()

    def _maybe_stall(self) -> None:
        with self._lock:
            self.requests += 1
            stall = self.requests > self.stall_after
            if stall:
                self.stalled_requests += 1
                n = self.requests
        if stall and not self._released.is_set():
            logger.warning("ParameterServerStallInjector: stalling "
                           "request %d", n)
            self._released.wait(self.stall_seconds)

    def pull(self):
        self._maybe_stall()
        return self._store.pull()

    def push_update(self, delta, **kwargs) -> None:
        # kwargs (e.g. request_id) pass through so idempotent retried
        # pushes stay idempotent with the injector in the middle
        self._maybe_stall()
        self._store.push_update(delta, **kwargs)

    @property
    def num_pushes(self) -> int:
        return self._store.num_pushes


class CheckpointCrashInjector:
    """Save-hook for `util/checkpoint_store.CheckpointStore`: kill the
    `fail_at_save`-th checkpoint save (1-based, at most `times` times) at
    a chosen `phase` of the commit protocol —

    - ``pre_write``: die before any byte is written,
    - ``mid_write``: truncate the temp payload to half its size (a
      partially flushed file) and die — the classic preemption-mid-save,
    - ``pre_publish``: payload + manifest fully written and fsynced but
      neither published,
    - ``post_payload``: payload published, manifest not — the narrowest
      crash window, leaving an unverifiable orphan the fallback loader
      must skip.

    In every case the store's atomic-commit contract says previously
    published checkpoints stay verified and loadable; the chaos suite
    proves save-crash → restart → resume-from-last-good end to end
    through `FaultTolerantTrainer` (wire via
    `FaultTolerantTrainer(..., save_hooks=[injector])`)."""

    PHASES = ("pre_write", "mid_write", "pre_publish", "post_payload")

    def __init__(self, phase: str = "mid_write", fail_at_save: int = 1,
                 times: int = 1):
        if phase not in self.PHASES:
            raise ValueError(f"unknown save phase {phase!r}; choose from "
                             f"{self.PHASES}")
        self.phase = phase
        self.fail_at_save = fail_at_save
        self.remaining = times
        self.fired = 0
        self.saves = 0
        self._lock = threading.Lock()

    def __call__(self, phase: str, step: int, path) -> None:
        with self._lock:
            if phase == "pre_write":
                self.saves += 1
            if (phase != self.phase or self.remaining <= 0
                    or self.saves < self.fail_at_save):
                return
            self.remaining -= 1
            self.fired += 1
        if phase == "mid_write":
            # leave a half-flushed temp file behind, like a real kill -9
            # between write() and fsync()
            import os

            size = os.path.getsize(path)
            with open(path, "rb+") as f:
                f.truncate(size // 2)
        logger.warning("CheckpointCrashInjector: injected crash during "
                       "checkpoint save (step %d, phase %s)", step, phase)
        raise InjectedFault(
            f"injected crash during checkpoint save (step {step}, "
            f"phase {phase})")


# ---------------------------------------------------------------------------
# data-poisoning injectors (health-sentinel chaos seams)


class _PoisonedDataSetIterator:
    """DataSetIterator-contract wrapper produced by
    `NaNGradientInjector.wrap` / `PoisonBatchInjector.wrap`: delegates the
    underlying iterator and runs every yielded batch through the
    injector. `async_supported` is False so injection order stays
    deterministic under chaos assertions (no prefetch races)."""

    def __init__(self, underlying, injector):
        self._u = underlying
        self._inj = injector

    def __iter__(self):
        self.reset()
        return self

    def __next__(self):
        if not self.has_next():
            raise StopIteration
        return self.next()

    def has_next(self):
        return self._u.has_next()

    def next(self):
        return self._inj._process(self._u.next())

    def reset(self):
        self._inj._on_reset()
        self._u.reset()

    def batch(self):
        return self._u.batch()

    @property
    def async_supported(self):
        return False


def _poisoned_copy(ds, value: float):
    """A features-poisoned COPY of `ds` (labels/masks shared; the
    original batch is never touched). Features become float32 — poisoning
    only makes sense for float-featured nets."""
    from deeplearning4j_tpu.datasets.dataset import DataSet

    import numpy as np

    bad = np.full(np.shape(ds.features), value, np.float32)
    return DataSet(bad, ds.labels, ds.features_mask, ds.labels_mask)


class NaNGradientInjector(TrainingHook):
    """TRANSIENT numeric blow-up: the `fail_at_fit`-th minibatch (1-based,
    counted across epochs/retries/replays, at most `times` times) gets
    its features replaced with `value` (NaN by default, try ``float('inf')``
    for the overflow flavor), so the fused train step's loss and gradients
    go non-finite at a chosen step — the health sentinel's skip guard is
    what keeps that from corrupting the parameters. Two seams:

    - ``wrap(iterator)`` — single-node fit loops: yields poisoned COPIES;
      the underlying batches stay clean, so a rollback replay trains on
      good data (a true transient, unlike `PoisonBatchInjector`).
    - `TrainingHook` (``worker.add_hook``) — distributed workers:
      `pre_update` poisons the shard batch in place and `post_update`
      restores the original features, so a re-dispatched shard trains
      clean while THIS worker's replica blows up (its non-finite result
      is then quarantined by the master —
      `training_master.NonFiniteWorkerResultError`). Restrict to one
      worker with `worker_id`.
    """

    def __init__(self, fail_at_fit: int = 1, times: int = 1,
                 value: float = float("nan"),
                 worker_id: Optional[int] = None):
        self.fail_at_fit = fail_at_fit
        self.remaining = times
        self.value = value
        self.worker_id = worker_id
        self.fired = 0
        self._fits = 0
        self._saved = {}
        self._lock = threading.Lock()

    def _trigger(self) -> bool:
        with self._lock:
            self._fits += 1
            if self._fits < self.fail_at_fit or self.remaining <= 0:
                return False
            self.remaining -= 1
            self.fired += 1
            fits = self._fits
        logger.warning("NaNGradientInjector: poisoning minibatch %d with "
                       "%s features", fits, self.value)
        return True

    # -- iterator seam ----------------------------------------------------
    def wrap(self, iterator) -> _PoisonedDataSetIterator:
        return _PoisonedDataSetIterator(iterator, self)

    def _process(self, ds):
        return _poisoned_copy(ds, self.value) if self._trigger() else ds

    def _on_reset(self) -> None:
        pass  # fits count across resets: a replay sees clean data once
        # `times` is spent — the transient contract

    # -- TrainingHook seam ------------------------------------------------
    def pre_update(self, ds, net) -> None:
        if self.worker_id is not None \
                and current_worker_id() != self.worker_id:
            return
        if not self._trigger():
            return
        import numpy as np

        with self._lock:
            self._saved[id(ds)] = ds.features
        ds.features = np.full(np.shape(ds.features), self.value,
                              np.float32)

    def post_update(self, ds, net) -> None:
        with self._lock:
            orig = self._saved.pop(id(ds), None)
        if orig is not None:
            ds.features = orig  # transient: re-dispatch sees clean data


class PoisonBatchInjector(TrainingHook):
    """PERSISTENT data poisoning: the record(s) at stream position
    `poison_at` (0-based int or collection of ints; position counts from
    the last `reset()`) have their features replaced with `value` EVERY
    time they are seen — retries, re-dispatches, and rollback replays
    included. A genuinely bad record, not a transient blow-up: the path
    that must end in quarantine (streaming tier) or a typed
    `TrainingDivergedError` (exhausted sentinel budget), never a hang.

    Seams: ``wrap(iterator)`` (DataSetIterator), ``wrap_source(source)``
    (plain streaming iterable — also accepts `(features, labels)` tuple
    records), and `TrainingHook` `pre_update` (poisons the shard batch in
    place with NO restore — the poison sticks to the shard across
    re-dispatches, so a data-poisoned shard fails on every worker and
    surfaces as `WorkerFailureError`)."""

    def __init__(self, poison_at=0, value: float = float("nan"),
                 worker_id: Optional[int] = None):
        self.poison_at = ({poison_at} if isinstance(poison_at, int)
                          else set(poison_at))
        self.value = value
        self.worker_id = worker_id
        self.fired = 0
        self._pos = 0
        self._fits = 0
        self._lock = threading.Lock()

    def _note_fired(self, pos: int) -> None:
        self.fired += 1
        logger.warning("PoisonBatchInjector: poisoned record at position "
                       "%d (%s features)", pos, self.value)

    # -- iterator seam ----------------------------------------------------
    def wrap(self, iterator) -> _PoisonedDataSetIterator:
        return _PoisonedDataSetIterator(iterator, self)

    def _process(self, ds):
        with self._lock:
            pos = self._pos
            self._pos += 1
            hit = pos in self.poison_at
        if not hit:
            return ds
        self._note_fired(pos)
        return _poisoned_copy(ds, self.value)

    def _on_reset(self) -> None:
        with self._lock:
            self._pos = 0  # persistent: the SAME positions re-poison
            # on every pass/replay

    def wrap_source(self, source):
        """Poisoning pass-through for a streaming source (plain
        iterable of DataSets or `(features, labels)` records)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        def gen():
            for item in source:
                ds = item if isinstance(item, DataSet) else DataSet(*item)
                yield self._process(ds)

        return gen()

    # -- TrainingHook seam ------------------------------------------------
    def pre_update(self, ds, net) -> None:
        if self.worker_id is not None \
                and current_worker_id() != self.worker_id:
            return
        import numpy as np

        with self._lock:
            pos = self._fits
            self._fits += 1
            hit = pos in self.poison_at
        if not hit:
            return
        self._note_fired(pos)
        ds.features = np.full(np.shape(ds.features), self.value,
                              np.float32)  # in place, never restored


# ---------------------------------------------------------------------------
# restart-driving trainer


class FaultTolerantTrainer:
    """Usage:

        trainer = FaultTolerantTrainer(net, iterator, checkpoint_dir=dir,
                                       checkpoint_every=50, max_restarts=3)
        trainer.fit(epochs=10)

    `net` may be a bare network OR a distributed handle
    (`DistributedMultiLayer`, `ParallelWrapper`, ...): anything exposing
    `fit(iterator, epochs=)` plus `get_network()` for the underlying
    network that checkpoints/restores — so worker-pool averaging and the
    sharded multi-chip path compose with checkpoint recovery.

    The iterator must be restartable (reset()-able); after a restore the
    current epoch is re-run from its start — batches before the checkpoint
    are re-applied only if they came after the last checkpoint, which is
    the at-least-once semantics checkpoint-interval recovery gives.

    On every restore, listeners implementing `on_restart(model, count)`
    are notified, and when the handle's TrainingMaster collects stats the
    restart is counted there as `restarts`.

    Checkpoints commit durably (`util/checkpoint_store.CheckpointStore`:
    atomic publish + integrity manifest), and a restore walks backwards
    to the newest checkpoint that still VERIFIES — a crash during a save
    (even one injected by `CheckpointCrashInjector` via `save_hooks`)
    costs at most the batches since the previous checkpoint, never the
    ability to restore. `CheckpointCorruptError` is raised only when no
    retained checkpoint survives.
    """

    def __init__(self, net, iterator, checkpoint_dir,
                 checkpoint_every: int = 100, max_restarts: int = 3,
                 keep_last: int = 2, propagate: tuple = (),
                 save_hooks=(), sentinel=None):
        # `propagate`: exception types that are CONTROL FLOW, not failures
        # (e.g. early stopping's iteration-abort) — re-raised immediately
        # instead of triggering a checkpoint restore
        self.propagate = propagate
        # `sentinel`: a `optimize.health.HealthSentinel` to attach to the
        # network (bare MultiLayerNetwork only — a distributed handle's
        # replicas/sharded step never consult it, so attach is refused
        # loudly there; that tier is guarded by the master's non-finite
        # result quarantine). The trainer then serves as the sentinel's
        # rollback driver: a `DivergenceRollback` restores the last
        # verified-good checkpoint and replays (counted as `rollbacks`,
        # never against `max_restarts`), and the typed
        # `TrainingDivergedError` always propagates (an exhausted
        # divergence budget is not a transient)
        self.sentinel = sentinel
        self.rollbacks = 0
        self.net = net
        # the restorable network behind a distributed handle/wrapper
        self.target = net.get_network() if hasattr(net, "get_network") \
            else net
        self.iterator = iterator
        self.checkpoint_dir = str(checkpoint_dir)
        self.max_restarts = max_restarts
        self.restarts = 0
        self._snapshot_known_good = False
        self._ckpt = CheckpointListener(self.checkpoint_dir,
                                        every_n_iterations=checkpoint_every,
                                        keep_last=keep_last,
                                        save_hooks=save_hooks)
        self.checkpoint_store = self._ckpt.store

    def _master_stats(self):
        master = getattr(self.net, "training_master", None)
        return master.get_training_stats() if master is not None else None

    def _restore(self) -> bool:
        """Restore the newest checkpoint that passes manifest
        verification AND loads, skipping corrupt/partial entries
        backwards (last-good fallback). Raises `CheckpointCorruptError`
        when checkpoints exist but none survive; returns False only when
        the store is empty."""
        from deeplearning4j_tpu.util.serialization import restore_model

        store = self.checkpoint_store
        if not store.steps():
            return False
        restored, step = store.load_latest_verified(restore_model)
        net = self.target
        net.set_params(restored.params())
        net._upd_state = restored._upd_state
        net._layer_state = restored._layer_state
        net.iteration = restored.iteration
        net.epoch = restored.epoch
        net._it_device = None  # resync from the host clock on next fit
        logger.warning("restored %s (iteration %d)", store.path_for(step),
                       net.iteration)
        return True

    def fit(self, epochs: int = 1, iterator=None) -> None:
        if iterator is not None:
            self.iterator = iterator
        net = self.target
        listeners = list(net.listeners)
        if self._ckpt not in listeners:
            net.set_listeners(*(listeners + [self._ckpt]))
        net._ensure_init()
        if self.sentinel is not None:
            if self.net is not net \
                    or not hasattr(net, "set_health_sentinel"):
                # fail LOUDLY: a distributed handle drives worker clones /
                # its own sharded step, neither of which consults the
                # sentinel — attaching one would be silently inert, the
                # exact silent-NaN outcome the sentinel exists to prevent
                raise ValueError(
                    "sentinel= requires a network whose own fit path runs "
                    "the guarded step (MultiLayerNetwork); "
                    f"{type(self.net).__name__} drives replicas/sharded "
                    "steps that never consult it — the distributed tier "
                    "is guarded by the master's non-finite result "
                    "quarantine (NonFiniteWorkerResultError) instead")
            self.sentinel.rollback_available = True
            if net.get_health_sentinel() is not self.sentinel:
                net.set_health_sentinel(self.sentinel)
        from deeplearning4j_tpu.util.checkpoint_store import (
            CheckpointCorruptError,
        )

        # the "do we have a restorable checkpoint" probe re-hashes full
        # payloads, and this fit() runs once per epoch under
        # EarlyStoppingDistributedTrainer — once a good checkpoint is
        # known to exist it stays monotonically true (our own saves only
        # add more), so check at most once per trainer
        if not self._snapshot_known_good:
            try:
                have_good = (self.checkpoint_store.latest_verified()
                             is not None)
            except CheckpointCorruptError:
                have_good = False  # all retained damaged: snapshot now
            if not have_good:
                # a fault BEFORE the first cadence checkpoint must still
                # roll back (otherwise pre-fault batches get re-applied
                # on retry)
                self._ckpt._save(net, net.iteration)
            self._snapshot_known_good = True
        done = 0
        while done < epochs:
            try:
                self.net.fit(self.iterator, epochs=1)
                done += 1
            except Exception as e:
                from deeplearning4j_tpu.optimize.health import (
                    DivergenceRollback,
                    TrainingDivergedError,
                )

                if isinstance(e, self.propagate) \
                        or isinstance(e, TrainingDivergedError):
                    # a typed divergence give-up is a verdict, not a
                    # transient — restoring and retrying would loop
                    raise
                rollback = isinstance(e, DivergenceRollback)
                if rollback:
                    # bounded by the SENTINEL's rollback_budget (it
                    # raises TrainingDivergedError when spent), so never
                    # charged against max_restarts
                    self.rollbacks += 1
                    logger.warning("divergence rollback %d: %s",
                                   self.rollbacks, e)
                else:
                    self.restarts += 1
                    if self.restarts > self.max_restarts:
                        logger.error("giving up after %d restarts",
                                     self.restarts - 1)
                        raise
                    logger.warning("training failed (%s: %s); restart %d/%d",
                                   type(e).__name__, e, self.restarts,
                                   self.max_restarts)
                if not self._restore():  # can't happen after the initial
                    raise RuntimeError(   # save; fail loudly if it does
                        "no checkpoint available to restore")
                master = getattr(self.net, "training_master", None)
                if master is not None and hasattr(master,
                                                  "reset_worker_health"):
                    # a restart is a fresh attempt: re-admit dropped
                    # workers, otherwise a transiently-dead pool (e.g. a
                    # brief PS outage that felled every worker) would fail
                    # every retry against the same empty pool
                    logger.warning("re-admitting all workers after restart")
                    master.reset_worker_health()
                stats = self._master_stats()
                if stats is not None:
                    stats.increment("rollbacks" if rollback else "restarts")
                hook_name = "on_rollback" if rollback else "on_restart"
                count = self.rollbacks if rollback else self.restarts
                for listener in getattr(net, "listeners", []):
                    listener_hook = getattr(listener, hook_name, None)
                    if listener_hook is not None:
                        listener_hook(net, count)
                if rollback:
                    sentinel = self.sentinel or getattr(
                        net, "get_health_sentinel", lambda: None)()
                    if sentinel is not None:
                        sentinel.on_rolled_back(net)
