"""Checkpoint-based fault tolerance for training loops.

Reference (SURVEY §5 "Failure detection / elastic recovery"): absent — the
reference inherits Spark task retry and nothing else; there is no
checkpoint-based elasticity and no fault-injection framework. Both are
table stakes for long TPU runs (preemptible pods), so this build provides:

- `FaultTolerantTrainer`: drives `net.fit` epoch-by-epoch with periodic
  checkpoints; on a transient failure it restores the newest checkpoint
  (model + updater state + iteration clock) and resumes, up to
  `max_restarts` times.
- `FaultInjectionListener`: deterministically raises at a chosen iteration
  — the fault-injection hook the recovery path is tested with.
"""
from __future__ import annotations

import logging
from typing import Optional

from deeplearning4j_tpu.optimize.listeners import (
    CheckpointListener,
    IterationListener,
)

logger = logging.getLogger("deeplearning4j_tpu")


class InjectedFault(RuntimeError):
    """Raised by FaultInjectionListener (distinguishable from real bugs)."""


class FaultInjectionListener(IterationListener):
    """Raises `InjectedFault` once training reaches `fail_at_iteration`
    (>=, so a restarted run that resumes past the trigger still fires);
    fires at most `times` times."""

    def __init__(self, fail_at_iteration: int, times: int = 1):
        self.fail_at_iteration = fail_at_iteration
        self.remaining = times
        self.fired = 0

    def iteration_done(self, model, iteration: int) -> None:
        if self.remaining > 0 and iteration >= self.fail_at_iteration:
            self.remaining -= 1
            self.fired += 1
            raise InjectedFault(
                f"injected fault at iteration {iteration}")


class FaultTolerantTrainer:
    """Usage:

        trainer = FaultTolerantTrainer(net, iterator, checkpoint_dir=dir,
                                       checkpoint_every=50, max_restarts=3)
        trainer.fit(epochs=10)

    The iterator must be restartable (reset()-able); after a restore the
    current epoch is re-run from its start — batches before the checkpoint
    are re-applied only if they came after the last checkpoint, which is
    the at-least-once semantics checkpoint-interval recovery gives.
    """

    def __init__(self, net, iterator, checkpoint_dir,
                 checkpoint_every: int = 100, max_restarts: int = 3,
                 keep_last: int = 2):
        self.net = net
        self.iterator = iterator
        self.checkpoint_dir = str(checkpoint_dir)
        self.max_restarts = max_restarts
        self.restarts = 0
        self._ckpt = CheckpointListener(self.checkpoint_dir,
                                        every_n_iterations=checkpoint_every,
                                        keep_last=keep_last)

    def _restore(self) -> bool:
        from deeplearning4j_tpu.util.serialization import restore_model

        path = CheckpointListener.last_checkpoint(self.checkpoint_dir)
        if path is None:
            return False
        restored = restore_model(path)
        net = self.net
        net.set_params(restored.params())
        net._upd_state = restored._upd_state
        net._layer_state = restored._layer_state
        net.iteration = restored.iteration
        net.epoch = restored.epoch
        net._it_device = None  # resync from the host clock on next fit
        logger.warning("restored %s (iteration %d)", path, net.iteration)
        return True

    def fit(self, epochs: int = 1) -> None:
        net = self.net
        listeners = list(net.listeners)
        if self._ckpt not in listeners:
            net.set_listeners(*(listeners + [self._ckpt]))
        net._ensure_init()
        if CheckpointListener.last_checkpoint(self.checkpoint_dir) is None:
            # a fault BEFORE the first cadence checkpoint must still roll
            # back (otherwise pre-fault batches get re-applied on retry)
            self._ckpt._save(net, net.iteration)
        done = 0
        while done < epochs:
            try:
                net.fit(self.iterator, epochs=1)
                done += 1
            except Exception as e:
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    logger.error("giving up after %d restarts", self.restarts - 1)
                    raise
                logger.warning("training failed (%s: %s); restart %d/%d",
                               type(e).__name__, e, self.restarts,
                               self.max_restarts)
                if not self._restore():  # can't happen after the initial
                    raise RuntimeError(   # save; fail loudly if it does
                        "no checkpoint available to restore")
