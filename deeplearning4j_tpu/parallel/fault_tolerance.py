"""Checkpoint-based fault tolerance + fault-injection framework.

Reference (SURVEY §5 "Failure detection / elastic recovery"): absent — the
reference inherits Spark task retry and nothing else; there is no
checkpoint-based elasticity and no fault-injection framework. Both are
table stakes for long TPU runs (preemptible pods), so this build provides:

- `FaultTolerantTrainer`: drives `fit` epoch-by-epoch with periodic
  checkpoints; on a transient failure it restores the newest checkpoint
  (model + updater state + iteration clock) and resumes, up to
  `max_restarts` times. Works on a bare network AND on distributed
  handles (`DistributedMultiLayer`, `ParallelWrapper`) — anything with a
  `fit(iterator, epochs=)` whose underlying network is reachable via
  `get_network()`.
- Fault injectors, all logging through the `deeplearning4j_tpu` logger so
  chaos tests assert on `caplog` rather than stdout:
  * `FaultInjectionListener` — single-node: raise at iteration N.
  * `WorkerCrashInjector` — distributed: worker k raises on its n-th fit.
  * `SlowWorkerInjector` — distributed: worker k sleeps per minibatch,
    exercising the master's straggler `worker_timeout`.
  * `ParameterServerStallInjector` — wraps a parameter-server store so
    push/pull block, exercising the client's timeout/backoff give-up.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from deeplearning4j_tpu.optimize.listeners import (
    CheckpointListener,
    IterationListener,
)
from deeplearning4j_tpu.parallel.training_master import (
    TrainingHook,
    current_worker_id,
)

logger = logging.getLogger("deeplearning4j_tpu")


class InjectedFault(RuntimeError):
    """Raised by fault injectors (distinguishable from real bugs)."""


class FaultInjectionListener(IterationListener):
    """Raises `InjectedFault` once training reaches `fail_at_iteration`
    (>=, so a restarted run that resumes past the trigger still fires);
    fires at most `times` times."""

    def __init__(self, fail_at_iteration: int, times: int = 1):
        self.fail_at_iteration = fail_at_iteration
        self.remaining = times
        self.fired = 0

    def iteration_done(self, model, iteration: int) -> None:
        if self.remaining > 0 and iteration >= self.fail_at_iteration:
            self.remaining -= 1
            self.fired += 1
            logger.warning("FaultInjectionListener: injected fault at "
                           "iteration %d", iteration)
            raise InjectedFault(
                f"injected fault at iteration {iteration}")


# ---------------------------------------------------------------------------
# distributed injectors (TrainingHook seam — attach via
# `ParameterAveragingTrainingWorker.add_hook`)


class WorkerCrashInjector(TrainingHook):
    """TrainingHook: worker `worker_id` raises `InjectedFault` in
    `pre_update` once it has seen `fail_at_fit` minibatches (1-based,
    counted across shards and retries), at most `times` times.
    Thread-safe: hooks fire concurrently from shard threads."""

    def __init__(self, worker_id: int, fail_at_fit: int = 1,
                 times: int = 1):
        self.worker_id = worker_id
        self.fail_at_fit = fail_at_fit
        self.remaining = times
        self.fired = 0
        self._fits = 0
        self._lock = threading.Lock()

    def pre_update(self, ds, net) -> None:
        if current_worker_id() != self.worker_id:
            return
        with self._lock:
            self._fits += 1
            if self._fits < self.fail_at_fit or self.remaining <= 0:
                return
            self.remaining -= 1
            self.fired += 1
            fits = self._fits
        logger.warning("WorkerCrashInjector: injected crash on worker %d "
                       "(fit %d)", self.worker_id, fits)
        raise InjectedFault(
            f"injected crash on worker {self.worker_id} (fit {fits})")


class SlowWorkerInjector(TrainingHook):
    """TrainingHook: worker `worker_id` sleeps `delay` seconds before each
    of its first `times` minibatches — a deterministic straggler to
    exercise the master's `worker_timeout` path. Keep `delay` bounded in
    tests: the hung shard thread runs to completion in the background (its
    result is discarded), and an unbounded sleep would outlive the test."""

    def __init__(self, worker_id: int, delay: float, times: int = 1):
        self.worker_id = worker_id
        self.delay = delay
        self.remaining = times
        self.fired = 0
        self._lock = threading.Lock()

    def pre_update(self, ds, net) -> None:
        if current_worker_id() != self.worker_id:
            return
        with self._lock:
            if self.remaining <= 0:
                return
            self.remaining -= 1
            self.fired += 1
        logger.warning("SlowWorkerInjector: delaying worker %d by %.2fs",
                       self.worker_id, self.delay)
        time.sleep(self.delay)


class ParameterServerStallInjector:
    """Wraps any pull/push parameter-server store; after `stall_after`
    successful requests, every request blocks for `stall_seconds` (or
    until `release()`) before reaching the store — the PS-stall chaos
    hook. Pair with `RetryingParameterServerClient` to prove a stalled
    server raises after bounded backoff instead of deadlocking."""

    def __init__(self, store, stall_after: int = 0,
                 stall_seconds: float = 3600.0):
        self._store = store
        self.stall_after = stall_after
        self.stall_seconds = stall_seconds
        self.requests = 0
        self.stalled_requests = 0
        self._released = threading.Event()
        self._lock = threading.Lock()

    def release(self) -> None:
        """Un-stall (lets background threads stuck in a stalled request
        finish promptly at test teardown)."""
        self._released.set()

    def _maybe_stall(self) -> None:
        with self._lock:
            self.requests += 1
            stall = self.requests > self.stall_after
            if stall:
                self.stalled_requests += 1
                n = self.requests
        if stall and not self._released.is_set():
            logger.warning("ParameterServerStallInjector: stalling "
                           "request %d", n)
            self._released.wait(self.stall_seconds)

    def pull(self):
        self._maybe_stall()
        return self._store.pull()

    def push_update(self, delta, **kwargs) -> None:
        # kwargs (e.g. request_id) pass through so idempotent retried
        # pushes stay idempotent with the injector in the middle
        self._maybe_stall()
        self._store.push_update(delta, **kwargs)

    @property
    def num_pushes(self) -> int:
        return self._store.num_pushes


# ---------------------------------------------------------------------------
# restart-driving trainer


class FaultTolerantTrainer:
    """Usage:

        trainer = FaultTolerantTrainer(net, iterator, checkpoint_dir=dir,
                                       checkpoint_every=50, max_restarts=3)
        trainer.fit(epochs=10)

    `net` may be a bare network OR a distributed handle
    (`DistributedMultiLayer`, `ParallelWrapper`, ...): anything exposing
    `fit(iterator, epochs=)` plus `get_network()` for the underlying
    network that checkpoints/restores — so worker-pool averaging and the
    sharded multi-chip path compose with checkpoint recovery.

    The iterator must be restartable (reset()-able); after a restore the
    current epoch is re-run from its start — batches before the checkpoint
    are re-applied only if they came after the last checkpoint, which is
    the at-least-once semantics checkpoint-interval recovery gives.

    On every restore, listeners implementing `on_restart(model, count)`
    are notified, and when the handle's TrainingMaster collects stats the
    restart is counted there as `restarts`.
    """

    def __init__(self, net, iterator, checkpoint_dir,
                 checkpoint_every: int = 100, max_restarts: int = 3,
                 keep_last: int = 2, propagate: tuple = ()):
        # `propagate`: exception types that are CONTROL FLOW, not failures
        # (e.g. early stopping's iteration-abort) — re-raised immediately
        # instead of triggering a checkpoint restore
        self.propagate = propagate
        self.net = net
        # the restorable network behind a distributed handle/wrapper
        self.target = net.get_network() if hasattr(net, "get_network") \
            else net
        self.iterator = iterator
        self.checkpoint_dir = str(checkpoint_dir)
        self.max_restarts = max_restarts
        self.restarts = 0
        self._ckpt = CheckpointListener(self.checkpoint_dir,
                                        every_n_iterations=checkpoint_every,
                                        keep_last=keep_last)

    def _master_stats(self):
        master = getattr(self.net, "training_master", None)
        return master.get_training_stats() if master is not None else None

    def _restore(self) -> bool:
        from deeplearning4j_tpu.util.serialization import restore_model

        path = CheckpointListener.last_checkpoint(self.checkpoint_dir)
        if path is None:
            return False
        restored = restore_model(path)
        net = self.target
        net.set_params(restored.params())
        net._upd_state = restored._upd_state
        net._layer_state = restored._layer_state
        net.iteration = restored.iteration
        net.epoch = restored.epoch
        net._it_device = None  # resync from the host clock on next fit
        logger.warning("restored %s (iteration %d)", path, net.iteration)
        return True

    def fit(self, epochs: int = 1, iterator=None) -> None:
        if iterator is not None:
            self.iterator = iterator
        net = self.target
        listeners = list(net.listeners)
        if self._ckpt not in listeners:
            net.set_listeners(*(listeners + [self._ckpt]))
        net._ensure_init()
        if CheckpointListener.last_checkpoint(self.checkpoint_dir) is None:
            # a fault BEFORE the first cadence checkpoint must still roll
            # back (otherwise pre-fault batches get re-applied on retry)
            self._ckpt._save(net, net.iteration)
        done = 0
        while done < epochs:
            try:
                self.net.fit(self.iterator, epochs=1)
                done += 1
            except Exception as e:
                if isinstance(e, self.propagate):
                    raise
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    logger.error("giving up after %d restarts", self.restarts - 1)
                    raise
                logger.warning("training failed (%s: %s); restart %d/%d",
                               type(e).__name__, e, self.restarts,
                               self.max_restarts)
                if not self._restore():  # can't happen after the initial
                    raise RuntimeError(   # save; fail loudly if it does
                        "no checkpoint available to restore")
                master = getattr(self.net, "training_master", None)
                if master is not None and hasattr(master,
                                                  "reset_worker_health"):
                    # a restart is a fresh attempt: re-admit dropped
                    # workers, otherwise a transiently-dead pool (e.g. a
                    # brief PS outage that felled every worker) would fail
                    # every retry against the same empty pool
                    logger.warning("re-admitting all workers after restart")
                    master.reset_worker_health()
                stats = self._master_stats()
                if stats is not None:
                    stats.increment("restarts")
                for listener in getattr(net, "listeners", []):
                    listener_hook = getattr(listener, "on_restart", None)
                    if listener_hook is not None:
                        listener_hook(net, self.restarts)
