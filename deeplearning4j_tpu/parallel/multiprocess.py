"""Multi-process / multi-host distributed training — the DCN tier.

Reference role: the Spark stack is the reference's genuinely multi-node
path — driver/executor JVMs over TCP shipping full parameter vectors
(`spark/impl/paramavg/ParameterAveragingTrainingMaster.java:75`,
`SparkDl4jMultiLayer.java:216`), with Aeron UDP for the async variant
(`ParameterServerParallelWrapper.java:160-218`).

TPU-native redesign: there is no driver/executor split and no parameter
shipping. Every process calls `initialize_multiprocess` (the
`jax.distributed` runtime — on real pods each host sees its own chips over
ICI, with DCN linking hosts; on CPU test rigs Gloo links the processes),
builds the SAME network from the same config/seed, and compiles the SAME
SPMD train step over ONE GLOBAL MESH spanning every process's devices.
XLA inserts the cross-process collectives: the gradient psum rides ICI
within a slice and DCN across hosts, inside the compiled step — the
"averaging" the Spark master did with a tree-reduce of full parameter
vectors every N iterations happens every step at interconnect speed.

Each process feeds only its LOCAL rows of the global batch
(`host_local_array_to_global_array` — the data-loading contract of every
multi-host JAX pipeline); parameters are replicated (or sharded per
`param_specs`) across the global mesh.

Validated without a cluster the same way the reference validates Spark
without one (`BaseSparkTest.java:89-90` `local[N]`): the test suite and
the driver's dryrun spawn 2 OS processes × N/2 virtual CPU devices each,
train same-seed, and require parameter parity with single-process
training (`TestCompareParameterAveragingSparkVsSingleMachine` analogue).
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.training_master import TrainingMaster
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

logger = logging.getLogger("deeplearning4j_tpu")


def initialize_multiprocess(coordinator_address: str, num_processes: int,
                            process_id: int,
                            local_device_count: Optional[int] = None) -> None:
    """Join the multi-process runtime (reference analogue: a Spark executor
    registering with the driver — but here every process is a peer running
    the same SPMD program). Must be called before any other JAX API.

    `local_device_count`: force N virtual CPU devices in THIS process
    (test rigs); on real TPU hosts leave None — each host contributes its
    attached chips."""
    import os

    if local_device_count is not None:
        import re

        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                      flags)
        if m and int(m.group(1)) != local_device_count:
            # force the EXACT count: a pre-existing larger value would make
            # this process contribute more local devices than its peers
            # expect, so the global mesh shape diverges across processes
            # (collective hang or wrong sharding); a smaller one would
            # silently shrink this process's mesh contribution
            flags = flags.replace(
                m.group(0), f"--xla_force_host_platform_device_count="
                f"{local_device_count}")
            os.environ["XLA_FLAGS"] = flags
        elif not m:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{local_device_count}").strip()
        jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    logger.info("multiprocess runtime up: process %d/%d, %d local / %d "
                "global devices", process_id, num_processes,
                jax.local_device_count(), jax.device_count())


def global_mesh(data_axis: str = "data") -> Mesh:
    """One mesh over EVERY process's devices (the global SPMD view)."""
    return Mesh(np.array(jax.devices()), (data_axis,))


class MultiProcessParallelWrapper(ParallelWrapper):
    """ParallelWrapper over a GLOBAL multi-process mesh.

    Same user surface as ParallelWrapper; the differences are the
    multi-host data contract (each process passes its LOCAL batch rows;
    the wrapper assembles the global sharded batch) and score reads
    (local shard of the replicated loss).
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 data_axis: str = "data",
                 param_specs: Optional[Dict] = None,
                 prefetch_buffer: int = 2):
        if jax.process_count() < 2:
            logger.warning(
                "MultiProcessParallelWrapper constructed with a single "
                "process — plain ParallelWrapper covers this case")
        if net.conf.tbptt_fwd_length > 0:
            raise NotImplementedError(
                "tBPTT under the multi-process wrapper is not supported "
                "yet; use single-process ParallelWrapper for recurrent "
                "windowed training")
        mesh = mesh if mesh is not None else global_mesh(data_axis)
        super().__init__(net, mesh=mesh, data_axis=data_axis,
                         param_specs=param_specs,
                         prefetch_buffer=prefetch_buffer)

    # local rows only need to split over LOCAL devices; the global batch is
    # the concatenation over processes (host_local_array_to_global_array)
    @property
    def num_local_devices(self) -> int:
        pi = jax.process_index()
        return sum(1 for d in self.mesh.devices.flat
                   if d.process_index == pi)

    def _shard_batch(self, ds):
        """HARD divisibility requirement, no silent trim/drop: every
        process must execute the SAME compiled step in lockstep — a
        per-process drop or trim would desynchronize the cross-process
        collectives (one host waiting forever in a psum while another
        skipped the step)."""
        n = self.num_local_devices
        B = ds.num_examples()
        if B % n:
            raise ValueError(
                f"local batch of {B} rows is not divisible by the "
                f"{n} local devices; multi-process SPMD training cannot "
                "trim per process (collective lockstep) — size local "
                "batches as a multiple of the local device count")
        return ds

    def _globalize(self, a):
        """Local host rows -> global array sharded on the data axis."""
        if a is None:
            return None
        from jax.experimental import multihost_utils as mh

        return mh.host_local_array_to_global_array(
            np.asarray(a), self.mesh, P(self.data_axis))

    def fit(self, data, epochs: int = 1) -> None:
        """Every process calls fit with its OWN local portion of the data
        stream (same number of batches everywhere — SPMD lockstep); the
        global batch per step is the concatenation across processes."""
        from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
        from deeplearning4j_tpu.datasets.iterators import (
            DataSetIterator,
            ListDataSetIterator,
        )

        from deeplearning4j_tpu.datasets.iterators import (
            AsyncDataSetIterator,
        )

        net = self.net
        if isinstance(data, (DataSet, MultiDataSet)):
            iterator: DataSetIterator = ListDataSetIterator([data])
        else:
            iterator = data
        if iterator.async_supported and not isinstance(
                iterator, AsyncDataSetIterator):
            iterator = AsyncDataSetIterator(iterator, self.prefetch_buffer)
        import jax.numpy as jnp

        net._it_device = jax.device_put(
            jnp.asarray(net.iteration, jnp.int32), self._repl)
        for _ in range(epochs):
            for listener in net.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(net)
            for ds in iterator:
                ds = self._shard_batch(ds)
                if ds is None:
                    continue
                net._validate_labels(ds)
                f, l, fm, lm = net._batch_arrays(ds)
                gf = jax.tree.map(self._globalize, (f, l, fm, lm),
                                  is_leaf=lambda x: x is None)
                (net._params, net._upd_state, net._layer_state,
                 net._it_device, loss) = self._jit_step(
                    net._params, net._upd_state, net._layer_state,
                    net._it_device, *gf)
                # replicated loss: keep the local shard (np.asarray on a
                # non-fully-addressable global array would raise)
                net._score = loss.addressable_shards[0].data
                net.iteration += 1
                for listener in net.listeners:
                    if hasattr(listener, "record_batch"):
                        listener.record_batch(
                            ds.num_examples() * jax.process_count())
                    listener.iteration_done(net, net.iteration)
            for listener in net.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(net)
            net.epoch += 1

    def local_params(self) -> np.ndarray:
        """Flat parameter vector from the local shards (params are
        replicated, so every process sees the same values)."""
        from jax.flatten_util import ravel_pytree

        local = jax.tree.map(lambda a: a.addressable_shards[0].data
                             if hasattr(a, "addressable_shards") else a,
                             self.net._params)
        flat, _ = ravel_pytree(local)
        return np.asarray(flat)


class MultiProcessTrainingMaster(TrainingMaster):
    """TrainingMaster SPI adapter for the multi-process tier (the seam the
    reference's Spark master occupied). `execute_training` runs in EVERY
    process with that process's local data partition; the global mesh step
    replaces the master's average-and-broadcast round."""

    def __init__(self, data_axis: str = "data", param_specs=None):
        self.data_axis = data_axis
        self.param_specs = param_specs
        self._wrapper: Optional[MultiProcessParallelWrapper] = None

    def execute_training(self, net, iterator) -> None:
        if self._wrapper is None or self._wrapper.net is not net:
            self._wrapper = MultiProcessParallelWrapper(
                net, data_axis=self.data_axis,
                param_specs=self.param_specs)
        self._wrapper.fit(iterator)

    def get_training_stats(self):
        return None


def free_port() -> int:
    """A free localhost TCP port for the coordinator (test/dryrun rigs)."""
    import socket

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_workers(cmds, env, timeout: int = 240):
    """Run worker subprocesses CONCURRENTLY (threaded communicate) and
    kill every worker on timeout/failure — a sequential communicate would
    leak live workers and can deadlock on an undrained stdout pipe while
    the sibling blocks in a collective."""
    import pathlib
    import subprocess
    import threading

    # workers import this package with `-m`: anchor their cwd at the repo
    # root so the spawn works regardless of the caller's cwd
    root = str(pathlib.Path(__file__).resolve().parents[2])
    procs = [subprocess.Popen(c, env=env, cwd=root, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT) for c in cmds]
    logs = [None] * len(procs)

    def drain(i):
        try:
            out, _ = procs[i].communicate(timeout=timeout)
            logs[i] = out.decode(errors="replace")
        except Exception as e:
            logs[i] = f"<communicate failed: {e}>"

    threads = [threading.Thread(target=drain, args=(i,))
               for i in range(len(procs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout + 30)
    for p in procs:
        if p.poll() is None:
            p.kill()
            p.communicate()
    return procs, logs


def _parity_fixture_net():
    """The fixture model shared by the worker entry, the pytest parity
    test, and the driver dryrun — ONE definition so the three runs cannot
    drift apart."""
    import deeplearning4j_tpu as dl4j
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.ops.activations import Activation
    from deeplearning4j_tpu.ops.losses import LossFunction

    conf = (dl4j.NeuralNetConfiguration.Builder()
            .seed(77).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=6, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_in=16, n_out=3,
                               activation=Activation.SOFTMAX,
                               loss=LossFunction.MCXENT))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def _parity_worker_main() -> None:
    """Entry point for the no-cluster validation (tests + driver dryrun):
    `python -m deeplearning4j_tpu.parallel.multiprocess <pid> <nprocs>
    <coordinator> <local_devices> <out_path>` — joins the runtime, trains
    the fixture model on this process's half of a deterministic data
    stream, and writes the final flat params (process 0)."""
    import sys

    pid = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    coordinator = sys.argv[3]
    local_devices = int(sys.argv[4])
    out_path = sys.argv[5]
    initialize_multiprocess(coordinator, nprocs, pid,
                            local_device_count=local_devices)

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    net = _parity_fixture_net()
    feats, labels = _parity_fixture_data()
    B = feats.shape[1]
    lo, hi = pid * (B // nprocs), (pid + 1) * (B // nprocs)
    batches = [DataSet(feats[i, lo:hi], labels[i, lo:hi])
               for i in range(feats.shape[0])]
    pw = MultiProcessParallelWrapper(net)
    pw.fit(ListDataSetIterator(batches), epochs=3)
    if pid == 0:
        np.save(out_path, pw.local_params())
        print(f"DCN_PARITY params saved ({pw.local_params().shape[0]} "
              f"values), loss={float(np.asarray(net._score)):.6f}",
              flush=True)


def _parity_fixture_data():
    """Deterministic fixture stream shared by every process and the
    single-process reference."""
    rng = np.random.RandomState(123)
    feats = rng.randn(6, 16, 6).astype(np.float32)      # 6 batches of 16
    labels = np.eye(3, dtype=np.float32)[rng.randint(0, 3, (6, 16))]
    return feats, labels


if __name__ == "__main__":
    _parity_worker_main()
