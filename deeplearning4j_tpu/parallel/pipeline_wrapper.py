"""PipelineParallelWrapper: GPipe pipeline training for a real network.

Reference seam: `ParallelWrapper.java:46-52` — wrap a built network,
train it across devices without changing the model code. The reference's
only strategy is data parallelism (SURVEY §2.4); this wrapper adds the
TPU-native pipeline axis: the network's layer stack is PARTITIONED into
stages (one per device on the `pipe` mesh axis) and microbatches flow
stage-to-stage over ICI via the `parallel/pipeline.py` GPipe schedule
(`lax.ppermute` inside one jitted fori_loop; `jax.grad` through it yields
the reverse-direction backward pipeline automatically).

Partitioning: the wrapper finds the longest contiguous run of
config-identical, shape-preserving, stateless layers (the transformer /
MLP trunk — where the depth actually lives), assigns `run_len // S`
consecutive layers to each of the S stages, and keeps everything before
(head: embeddings, preprocessors) and after (tail: output head) replicated
on every device — the standard split for models whose head/tail are a few
percent of the parameters. Stage parameters are STACKED on a leading axis
and sharded over `pipe`, so each device holds only its own stage's
weights; the updater math (elementwise over leaves) runs directly on the
stacked/sharded pytrees — no gather, no per-stage hosts.

Dropout IS supported in the trunk (r5): each stage derives its true
layer's PRNG key (`fold_in(rng, trunk_start + stage*k + j)` with the
traced stage index) and dropout draws partition-invariant per-row masks
(`ops/rng_rows`), so a pipelined dropout>0 net reproduces single-device
training bit-for-seed. Tensor parallelism composes in the same mesh
(r5): pass `model_axis="model"` and the stacked stage params are
additionally sharded Megatron-style over that axis (Wqkv/W1 column,
Wo/W2 row for a TransformerBlock trunk); the shard_map keeps only
{pipe, data} manual, so the SPMD partitioner owns the model axis and
inserts its collectives inside each stage — dp x tp x pp in one jit.

Remaining restrictions (declined loudly, with the quantitative reason):
- BatchNormalization trunks: BN computes BATCH statistics; a GPipe stage
  sees one microbatch (B/M rows) per tick, so its normalizer would use
  B/M-row moments where single-device training uses B-row moments — a
  semantic change (noisier stats, different running averages), not a
  numerical tolerance. Cross-microbatch sync inside the fori_loop would
  serialize the pipeline (each tick would need all M microbatches'
  activations — exactly what the schedule exists to avoid). Use
  ParallelWrapper: under dp the global-view jit computes full-batch
  moments regardless of sharding.
- MoE trunks: `switch_ffn`'s load-balancing aux loss rides a trace-time
  side channel (`ops/aux_loss`) that collects per CALL; inside the
  pipeline fori_loop the trunk body executes once per TICK on garbage
  fill/drain slots too, and the aux term of microbatch m exists only on
  stage s at tick s+m — summing it correctly requires threading an
  extra carry through the loop AND masking fill/drain ticks. Doable,
  but the capacity-overflow semantics would still differ (per-microbatch
  capacity vs global capacity). Replicated MoE head/tail blocks work
  (they run in the global view); expert-parallel MoE composes with dp
  via ParallelWrapper instead.
- masks and tBPTT stay on ParallelWrapper.

Same-seed loss parity vs single-device training is the correctness bar
(`tests/test_pipeline_wrapper.py`, incl. the GPT TransformerBlock trunk
with dropout and the 3-D dp x tp x pp mesh), the analogue of the
reference's `TestCompareParameterAveragingSparkVsSingleMachine`.

Schedule & bubble: GPipe with M microbatches over S stages runs
S + M - 1 pipeline ticks, of which S - 1 are fill/drain — the bubble
fraction is (S - 1) / (S + M - 1), and jax.grad mirrors the same
schedule backward, so the end-to-end bubble is ~2(S-1)/(2(S+M-1)) ==
the forward fraction. Microbatch guidance: M defaults to S (bubble
(S-1)/(2S-1), just under 50% idle); raise M toward 4S for a <20%
bubble when the global batch allows (per-microbatch size B/M must stay
large enough to feed the MXU — shrinking below ~128 rows per stage
trades bubble for underutilized matmuls). Memory: GPipe stashes
activations for all M in-flight microbatches; set TransformerBlock
remat=True to rematerialize blocks in the backward and hold O(1)
residuals per stage instead.

Why not 1F1B: under a single-jit SPMD schedule it is strictly
dominated by GPipe+remat, and the reason is quantitative, not
taste. 1F1B's selling point is capping the activation stash at S
in-flight microbatches (vs GPipe's M) without remat's recompute.
But lock-step execution — the only form a single jitted fori_loop
with ppermute barriers can express — quantizes the schedule into
global ticks, and in 1F1B's steady state each stage runs its
forward on every OTHER tick (f(d,m) = 2m + 2d - S + 1: adjacent
stages alternate parity, and the one-tick hop latency in BOTH
directions forces the 2m stride), so half of every device's slots
idle even at peak. Counting fwd = 1, bwd = 2 units: lock-step 1F1B
needs 2(S+M-2) ticks x 3 units = 6(S+M-2) per batch, while GPipe
is 3(S+M-1) and GPipe+remat — which already achieves a BETTER
memory bound (O(1) stashed microbatch inputs per stage, blocks
recomputed in the backward) — is 4(S+M-1). The asynchronous MPMD
execution that makes real 1F1B pay (each stage free-running its
own program, fwd/bwd packed back-to-back with no tick barrier)
is exactly what XLA's single-program model does not express; a
double-pumped variant (two interleaved 1F1B streams filling the
alternate-parity slots) restores utilization but doubles the stash
to 2S and only beats GPipe+remat's wall clock once M >> 8S, a
regime where per-microbatch MXU feed (B/M rows) has usually
collapsed first. Hence the chosen design point: GPipe for the
schedule, remat for the memory bound, ~1/3 extra trunk FLOPs as
the price — cheaper than lock-step 1F1B's idle slots in every
regime this wrapper targets.
"""
from __future__ import annotations

import logging
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.updater import apply_layer_update
from deeplearning4j_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)

logger = logging.getLogger("deeplearning4j_tpu")


def _layer_signature(net, i):
    """Homogeneity key: same config dataclass, same param shapes, and the
    layer maps its input type to itself (shape-preserving)."""
    layer = net.layers[i]
    it_in = net._input_types[i]
    it_out = layer.output_type(it_in)
    p = net._params[i]
    shapes = tuple(sorted((k, tuple(v.shape)) for k, v in p.items()))
    return (layer, shapes, repr(it_in), repr(it_out), repr(it_in) == repr(it_out))


def find_trunk(net, n_stages: int) -> Tuple[int, int]:
    """Longest contiguous run of pipeline-able identical layers, trimmed to
    a multiple of `n_stages`. Returns (start, end) layer indices
    (end exclusive). Raises with a diagnosis when nothing qualifies."""
    n = len(net.layers)
    best = (0, 0)
    i = 0
    while i < n - 1:  # the output layer can never join the trunk
        if not _pipelineable(net, i):
            i += 1
            continue
        sig0 = _layer_signature(net, i)
        j = i
        while (j < n - 1 and _pipelineable(net, j)
               and _signature_matches(sig0, _layer_signature(net, j))):
            j += 1
        if j - i > best[1] - best[0]:
            best = (i, j)
        i = j
    start, end = best
    usable = ((end - start) // n_stages) * n_stages
    if usable < n_stages:
        raise ValueError(
            f"no pipeline-able trunk: need >= {n_stages} contiguous "
            "identical stateless shape-preserving layers (found a best run "
            f"of {end - start}). BatchNormalization/MoE layers cannot join "
            "a pipeline stage (see the module docstring for the math); "
            "use ParallelWrapper (dp/tp) for such nets")
    return start, start + usable


def _signature_matches(a, b) -> bool:
    la, sa, ia, oa, pa = a
    lb, sb, ib, ob, pb = b
    return la == lb and sa == sb and ia == ib and pa and pb


def _pipelineable(net, i) -> bool:
    layer = net.layers[i]
    if i in net.conf.preprocessors or not layer.has_params:
        return False
    if net._layer_state[i]:  # stateful (BN running stats, LSTM carries)
        return False
    if getattr(layer, "moe_experts", 0):  # aux-loss side channel (docstring)
        return False
    sig = _layer_signature(net, i)
    return sig[4]  # shape-preserving


class PipelineParallelWrapper:
    """Usage:

        pw = PipelineParallelWrapper(net, mesh)   # mesh axis 'pipe'
        pw.fit(iterator, epochs=...)
        # wrapper syncs trained params back into `net` after each fit, so
        # net.evaluate()/save continue to work unchanged.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 pipe_axis: str = "pipe",
                 microbatches: Optional[int] = None,
                 data_axis: Optional[str] = None,
                 model_axis: Optional[str] = None,
                 model_specs: Optional[dict] = None,
                 prefetch_buffer: int = 2):
        """`data_axis`: 2-D dp x pp — give a mesh with BOTH axes (e.g.
        `make_mesh({"data": 2, "pipe": 4})`); batches shard over `data`,
        stages over `pipe`, and the SPMD partitioner inserts the gradient
        all-reduce over the data axis inside the step (the reference's
        averaging step, at ICI speed, composed with the pipeline).

        `model_axis`: 3-D dp x tp x pp — stage parameters are additionally
        TENSOR-sharded over this mesh axis inside each pipeline stage.
        `model_specs` maps trunk param names to PartitionSpecs WITHOUT the
        leading stage dim (e.g. {"W1": P(None, "model")}); omitted names
        replicate over the axis. When the trunk is a TransformerBlock
        stack the Megatron-style specs (Wqkv/W1/W3 column, Wo/W2 row) are
        derived automatically. The model axis stays AUTO in the pipeline
        shard_map, so XLA owns the tensor collectives — numerics are
        exactly the single-device math."""
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        if not hasattr(net, "layers"):
            raise ValueError(
                "PipelineParallelWrapper takes a MultiLayerNetwork (a "
                "linear layer stack to partition into stages); for a "
                "ComputationGraph express the trunk as an MLN or use "
                "ParallelWrapper (dp/tp), which supports both containers")
        net._ensure_init()
        if net.conf.tbptt_fwd_length > 0:
            raise ValueError("pipeline parallelism does not support tBPTT; "
                             "use ParallelWrapper for recurrent nets")
        self.net = net
        self.mesh = mesh if mesh is not None else make_mesh({pipe_axis: -1})
        if pipe_axis not in self.mesh.shape:
            raise ValueError(f"mesh has no '{pipe_axis}' axis: "
                             f"{dict(self.mesh.shape)}")
        if data_axis is not None and data_axis not in self.mesh.shape:
            raise ValueError(f"mesh has no '{data_axis}' axis: "
                             f"{dict(self.mesh.shape)}")
        if data_axis == pipe_axis:
            raise ValueError("data_axis must differ from pipe_axis "
                             f"({pipe_axis!r})")
        if model_axis is not None:
            if model_axis not in self.mesh.shape:
                raise ValueError(f"mesh has no '{model_axis}' axis: "
                                 f"{dict(self.mesh.shape)}")
            if model_axis in (pipe_axis, data_axis):
                raise ValueError(
                    f"model_axis {model_axis!r} must differ from the pipe "
                    f"and data axes")
        self.pipe_axis = pipe_axis
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.n_data = (1 if data_axis is None
                       else self.mesh.shape[data_axis])
        self.n_stages = self.mesh.shape[pipe_axis]
        self.microbatches = microbatches or self.n_stages
        self.prefetch_buffer = prefetch_buffer

        self.trunk_start, self.trunk_end = find_trunk(net, self.n_stages)
        # norm-based gradient normalization computes a PER-LAYER norm; on
        # the stage-STACKED trunk that norm would span all S stages jointly
        # and silently diverge from single-device training — refuse it
        from deeplearning4j_tpu.nn.updater import GradientNormalization

        _norm_kinds = {GradientNormalization.RENORMALIZE_L2_PER_LAYER,
                       GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE,
                       GradientNormalization.CLIP_L2_PER_LAYER,
                       GradientNormalization.CLIP_L2_PER_PARAM_TYPE}
        for i in range(self.trunk_start, self.trunk_end):
            cfg = net.layers[i].updater_cfg
            gn = getattr(cfg, "gradient_normalization", None)
            if gn in _norm_kinds:
                raise ValueError(
                    f"pipeline stages cannot use norm-based gradient "
                    f"normalization ({gn.value}): the norm would be "
                    "computed across all stacked stages instead of per "
                    "layer; use elementwise clipping or ParallelWrapper")
        self.layers_per_stage = (self.trunk_end
                                 - self.trunk_start) // self.n_stages
        logger.info(
            "pipeline: layers [%d, %d) -> %d stages x %d layers; head=%d "
            "tail=%d layers replicated", self.trunk_start, self.trunk_end,
            self.n_stages, self.layers_per_stage, self.trunk_start,
            len(net.layers) - self.trunk_end)

        self._repl = NamedSharding(self.mesh, P())
        self._stage_sh = NamedSharding(self.mesh, P(pipe_axis))
        self._batch_sh = (self._repl if data_axis is None
                          else NamedSharding(self.mesh, P(data_axis)))
        if model_axis is None:
            self._model_specs = {}
        elif model_specs is not None:
            self._model_specs = dict(model_specs)
        else:
            self._model_specs = self._derive_model_specs()

        # wrapper-owned layout: (head list, stacked trunk, tail list)
        self._split_from_net()
        self._jit_step = None

    def _derive_model_specs(self) -> dict:
        """Megatron-style tensor shardings for the trunk's param names.
        Column-shard the up-projections, row-shard the down-projections;
        norm scales/shifts and output biases replicate. These are HINTS on
        an auto axis — XLA propagates and inserts the collectives, so an
        imperfect spec costs communication, never correctness."""
        from deeplearning4j_tpu.nn.conf.layers import TransformerBlock

        ax = self.model_axis
        layer = self.net.layers[self.trunk_start]
        if isinstance(layer, TransformerBlock):
            return {"Wqkv": P(None, ax), "bqkv": P(ax),
                    "Wo": P(ax, None),
                    "W1": P(None, ax), "b1": P(ax), "W3": P(None, ax),
                    "W2": P(ax, None)}
        # generic dense trunk: column-shard the weight, split the bias
        return {"W": P(None, ax), "b": P(ax)}

    def _trunk_leaf_sh(self, name, arr) -> NamedSharding:
        """Sharding for one STACKED trunk leaf: stage axis over pipe, plus
        the layer's model-axis spec when the leaf is param-shaped (updater
        slots mirror their parameter; non-param-shaped slots stage-shard
        only)."""
        sp = self._model_specs.get(name, P())
        if len(sp) and arr.ndim - 1 < len(sp):
            sp = P()
        return NamedSharding(self.mesh, P(self.pipe_axis, *sp))

    # ------------------------------------------------------------- layout
    def _stage_group(self, tree_list, s):
        """Stage s's k consecutive per-layer entries."""
        k = self.layers_per_stage
        lo = self.trunk_start + s * k
        return [tree_list[lo + j] for j in range(k)]

    def _split_from_net(self):
        net = self.net
        S = self.n_stages
        self.head_params = [net._params[i] for i in range(self.trunk_start)]
        self.tail_params = [net._params[i]
                            for i in range(self.trunk_end, len(net.layers))]
        self.trunk_params = stack_stage_params(
            [self._stage_group(net._params, s) for s in range(S)])
        self.head_upd = [net._upd_state[i] for i in range(self.trunk_start)]
        self.tail_upd = [net._upd_state[i]
                         for i in range(self.trunk_end, len(net.layers))]
        self.trunk_upd = stack_stage_params(
            [self._stage_group(net._upd_state, s) for s in range(S)])
        # trunk layers are stateless; head/tail states stay as-is
        self.lstate = list(net._layer_state)

        # per-leaf trunk shardings: stage axis over pipe + the model-axis
        # tensor spec (identity when model_axis is None)
        self._trunk_sh = [
            {name: self._trunk_leaf_sh(name, arr)
             for name, arr in grp.items()}
            for grp in self.trunk_params]
        self._trunk_upd_sh = [
            {name: {slot: self._trunk_leaf_sh(name, sarr)
                    for slot, sarr in slots.items()}
             for name, slots in grp.items()}
            for grp in self.trunk_upd]

        self.head_params = jax.device_put(self.head_params, self._repl)
        self.tail_params = jax.device_put(self.tail_params, self._repl)
        self.trunk_params = jax.device_put(self.trunk_params, self._trunk_sh)
        self.head_upd = jax.device_put(self.head_upd, self._repl)
        self.tail_upd = jax.device_put(self.tail_upd, self._repl)
        self.trunk_upd = jax.device_put(self.trunk_upd, self._trunk_upd_sh)
        self.lstate = jax.device_put(self.lstate, self._repl)

    def sync_to_net(self) -> None:
        """Write trained parameters back into the wrapped network (unstack
        the trunk), so evaluate()/save/serialization see the result."""
        net = self.net
        S, k = self.n_stages, self.layers_per_stage
        params = list(self.head_params)
        upd = list(self.head_upd)
        for s in range(S):
            stage_p = jax.tree.map(lambda a: a[s], self.trunk_params)
            stage_u = jax.tree.map(lambda a: a[s], self.trunk_upd)
            params.extend(stage_p)
            upd.extend(stage_u)
        params.extend(self.tail_params)
        upd.extend(self.tail_upd)
        net._params = jax.device_put(params, jax.devices()[0])
        net._upd_state = jax.device_put(upd, jax.devices()[0])
        net._layer_state = jax.device_put(list(self.lstate),
                                          jax.devices()[0])
        net._jit_train = None  # placements changed; retrace lazily

    # --------------------------------------------------------------- loss
    def _loss_pipe(self, head_p, trunk_p, tail_p, lstate, features, labels,
                   fmask, lmask, rng):
        """The network's `_loss_pure` with the trunk replaced by the GPipe
        schedule. Head/tail math matches `MultiLayerNetwork._loss_pure`
        exactly (same rng folds per layer index) so single-device parity
        holds same-seed."""
        net = self.net
        train = True
        params_in = (head_p, trunk_p, tail_p)
        features = net._prep_features(features)
        if net.compute_dtype is not None:
            from deeplearning4j_tpu.nn.precision import tree_cast

            head_p, trunk_p, tail_p = tree_cast(
                (head_p, trunk_p, tail_p), net.compute_dtype)
            if not getattr(net.layers[0], "integer_input", False):
                features = features.astype(net.compute_dtype)
        from deeplearning4j_tpu.ops.aux_loss import aux_loss_scope

        new_state = list(lstate)
        with aux_loss_scope() as aux_terms:
            # mid-network aux losses (e.g. a replicated MoE head/tail
            # block's load-balancing term) collect exactly as in
            # `_loss_pure`; the trunk itself is MoE-free by construction
            x = features
            for i in range(self.trunk_start):
                layer = net.layers[i]
                lrng = None if rng is None else jax.random.fold_in(rng, i)
                if i in net.conf.preprocessors:
                    x = net.conf.preprocessors[i].preprocess(x, rng=lrng,
                                                             train=train)
                mask = fmask if x.ndim == 3 else None
                x, new_state[i] = layer.forward(head_p[i], lstate[i], x,
                                                train=train, rng=lrng,
                                                mask=mask)

            k = self.layers_per_stage
            trunk_layers = [net.layers[self.trunk_start + j]
                            for j in range(k)]
            from deeplearning4j_tpu.ops.rng_rows import row_offset_scope

            def block_fn(stage_p, xb, stage, row_off):
                # stage is the traced pipeline-stage index: fold the TRUE
                # layer index (trunk_start + stage*k + j) so per-layer keys
                # match `_loss_pure`'s fold exactly; row_off makes dropout
                # draw the same global-row masks a single device would
                for j in range(k):
                    lrng = (None if rng is None else jax.random.fold_in(
                        rng, self.trunk_start + stage * k + j))
                    with row_offset_scope(row_off):
                        xb, _ = trunk_layers[j].forward(
                            stage_p[j], {}, xb, train=train, rng=lrng,
                            mask=None)
                return xb

            x = pipeline_apply(block_fn, trunk_p, x, self.mesh,
                               axis_name=self.pipe_axis,
                               microbatches=self.microbatches,
                               data_axis=self.data_axis,
                               block_ctx=True)

            for idx, i in enumerate(range(self.trunk_end,
                                          len(net.layers) - 1)):
                layer = net.layers[i]
                lrng = None if rng is None else jax.random.fold_in(rng, i)
                if i in net.conf.preprocessors:
                    x = net.conf.preprocessors[i].preprocess(x, rng=lrng,
                                                            train=train)
                mask = fmask if x.ndim == 3 else None
                x, new_state[i] = layer.forward(tail_p[idx], lstate[i], x,
                                                train=train, rng=lrng,
                                                mask=mask)
        if net.compute_dtype is not None:
            from deeplearning4j_tpu.nn.precision import restore_dtypes

            x = x.astype(net.dtype)
            new_state = restore_dtypes(new_state, list(lstate))
        out_i = len(net.layers) - 1
        out_layer = net.layers[out_i]
        out_rng = None if rng is None else jax.random.fold_in(rng, out_i)
        if out_i in net.conf.preprocessors:
            x = net.conf.preprocessors[out_i].preprocess(x, rng=out_rng,
                                                         train=train)
        mask = lmask if lmask is not None else (fmask if x.ndim == 3 else None)
        head_pi, trunk_pi, tail_pi = params_in
        loss = out_layer.loss_score(tail_pi[-1], x, labels, train=train,
                                    rng=out_rng, mask=mask)
        loss = loss + self._reg_score(head_pi, trunk_pi, tail_pi)
        for term in aux_terms:  # replicated head/tail MoE load balancing
            loss = loss + term
        return loss, new_state

    def _reg_score(self, head_p, trunk_p, tail_p):
        """L1/L2 over every parameter. Stacked trunk leaves sum over the
        stage axis exactly like summing per-layer terms."""
        from deeplearning4j_tpu.nn.updater import regularization_score

        net = self.net
        pairs = list(zip(net.layers[:self.trunk_start], head_p))
        trunk_layers = [net.layers[self.trunk_start + j]
                        for j in range(self.layers_per_stage)]
        pairs += list(zip(trunk_layers, trunk_p))
        pairs += list(zip(net.layers[self.trunk_end:], tail_p))
        return regularization_score(pairs)

    # --------------------------------------------------------------- step
    def _make_step(self):
        net = self.net
        seed = net.conf.seed
        k = self.layers_per_stage

        def step(head_p, trunk_p, tail_p, head_u, trunk_u, tail_u, lstate,
                 iteration, features, labels, fmask, lmask):
            rng = jax.random.fold_in(jax.random.PRNGKey(seed), iteration)
            (loss, new_lstate), grads = jax.value_and_grad(
                self._loss_pipe, argnums=(0, 1, 2), has_aux=True)(
                head_p, trunk_p, tail_p, lstate, features, labels, fmask,
                lmask, rng)
            g_head, g_trunk, g_tail = grads
            nh, nt = [], []
            uh, ut = [], []
            for i in range(self.trunk_start):
                p, u = apply_layer_update(net.layers[i], head_u[i],
                                          head_p[i], g_head[i], iteration)
                nh.append(p)
                uh.append(u)
            # updater math is elementwise over leaves, so it applies to the
            # stage-STACKED trunk pytrees unchanged (each stage's slice gets
            # exactly the update its layer would get unstacked)
            ntr, utr = [], []
            for j in range(k):
                p, u = apply_layer_update(net.layers[self.trunk_start + j],
                                          trunk_u[j], trunk_p[j],
                                          g_trunk[j], iteration)
                ntr.append(p)
                utr.append(u)
            for idx, i in enumerate(range(self.trunk_end, len(net.layers))):
                p, u = apply_layer_update(net.layers[i], tail_u[idx],
                                          tail_p[idx], g_tail[idx],
                                          iteration)
                nt.append(p)
                ut.append(u)
            return nh, ntr, nt, uh, utr, ut, new_lstate, iteration + 1, loss

        repl, bsh = self._repl, self._batch_sh
        tsh, tush = self._trunk_sh, self._trunk_upd_sh
        return jax.jit(
            step,
            in_shardings=(repl, tsh, repl, repl, tush, repl, repl, repl,
                          bsh, bsh, bsh, bsh),
            out_shardings=(repl, tsh, repl, repl, tush, repl, repl, repl,
                           repl),
            donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7),
        )

    # ---------------------------------------------------------------- fit
    def fit(self, data: Union[DataSet, DataSetIterator],
            epochs: int = 1) -> None:
        net = self.net
        if isinstance(data, DataSet):
            iterator: DataSetIterator = ListDataSetIterator([data])
        else:
            iterator = data
        if (iterator.async_supported
                and not isinstance(iterator, AsyncDataSetIterator)):
            iterator = AsyncDataSetIterator(iterator, self.prefetch_buffer)
        if self._jit_step is None:
            self._jit_step = self._make_step()
        it_dev = jax.device_put(jnp.asarray(net.iteration, jnp.int32),
                                self._repl)
        try:
            for _ in range(epochs):
                for ds in iterator:
                    if ds.features_mask is not None or ds.labels_mask is not None:
                        raise ValueError(
                            "PipelineParallelWrapper does not support "
                            "masked batches; use ParallelWrapper")
                    B = ds.num_examples()
                    quantum = self.microbatches * self.n_data
                    if B % quantum:
                        usable = (B // quantum) * quantum
                        if not usable:
                            logger.warning("dropping batch of %d < %d "
                                           "(microbatches x data shards)",
                                           B, quantum)
                            continue
                        logger.warning("trimming batch %d -> %d "
                                       "(microbatch/data divisibility)",
                                       B, usable)
                        ds = DataSet(ds.features[:usable],
                                     None if ds.labels is None
                                     else ds.labels[:usable])
                    net._validate_labels(ds)
                    f, l, fm, lm = net._batch_arrays(ds)
                    (self.head_params, self.trunk_params, self.tail_params,
                     self.head_upd, self.trunk_upd, self.tail_upd,
                     self.lstate, it_dev, loss) = self._jit_step(
                        self.head_params, self.trunk_params,
                        self.tail_params, self.head_upd, self.trunk_upd,
                        self.tail_upd, self.lstate, it_dev, f, l, fm, lm)
                    net._score = loss
                    net.iteration += 1
                    for listener in net.listeners:
                        if hasattr(listener, "record_batch"):
                            listener.record_batch(ds.num_examples())
                        listener.iteration_done(net, net.iteration)
                net.epoch += 1
        finally:
            self.sync_to_net()
