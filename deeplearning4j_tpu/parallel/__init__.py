"""Distributed training — TPU equivalent of reference
`deeplearning4j-scaleout/`.

The reference's entire parallelism inventory is data parallelism over three
transports (SURVEY §2.4): in-process multi-GPU averaging
(`ParallelWrapper.java:179` `Nd4j.averageAndPropagate`), Spark parameter
averaging (`ParameterAveragingTrainingMaster.java:75`), and an Aeron UDP
parameter server (`ParameterServerParallelWrapper.java:39`).

Here all of it maps onto ONE mechanism: `jax.sharding.Mesh` + jit with
sharding annotations, letting XLA insert ICI collectives (psum all-reduce)
inside the compiled step — gradient averaging costs one fused all-reduce
instead of a host-mediated parameter ship. The same wrapper also supports
tensor-parallel parameter shardings (beyond the reference's capabilities),
which is the foundation the long-context/sequence-parallel modules build on.
"""

from deeplearning4j_tpu.parallel.mesh import make_mesh  # noqa: F401
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: F401
from deeplearning4j_tpu.parallel.early_stopping import (  # noqa: F401
    EarlyStoppingDistributedTrainer,
    EarlyStoppingParallelTrainer,
)
from deeplearning4j_tpu.parallel.fault_tolerance import (  # noqa: F401
    FaultInjectionListener,
    FaultTolerantTrainer,
    InjectedFault,
    NaNGradientInjector,
    ParameterServerStallInjector,
    PoisonBatchInjector,
    SlowWorkerInjector,
    WorkerCrashInjector,
)
from deeplearning4j_tpu.parallel.parameter_server import (  # noqa: F401
    ParameterServer,
    ParameterServerParallelWrapper,
    ParameterServerTimeoutError,
    RetryingParameterServerClient,
)
from deeplearning4j_tpu.parallel.repartition import (  # noqa: F401
    Repartition,
    RepartitionStrategy,
    balanced_partitions,
)
from deeplearning4j_tpu.parallel.stats import TrainingStats  # noqa: F401
from deeplearning4j_tpu.parallel.training_master import (  # noqa: F401
    DistributedComputationGraph,
    DistributedMultiLayer,
    NoHealthyWorkersError,
    NonFiniteWorkerResultError,
    ParameterAveragingTrainingMaster,
    ParameterAveragingTrainingWorker,
    TrainingHook,
    TrainingMaster,
    TrainingResult,
    TrainingWorker,
    WorkerFailureError,
    WorkerHealth,
    current_worker_id,
)
