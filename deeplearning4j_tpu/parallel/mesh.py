"""Device-mesh construction helpers.

TPU equivalent of the reference's device enumeration in `ParallelWrapper`
(one CUDA device per worker thread). Here: an N-d logical mesh over the
chips; shardings name mesh axes and XLA routes the collectives over ICI.

The serving tier builds its tensor-parallel decode mesh separately
(`serving.tp_engine.tp_mesh`: a 1-d `("tp",)` mesh over the FIRST N
devices, cached per degree) because a serving process typically owns a
sub-slice, not the whole topology these training helpers assume.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from {axis_name: size}. Sizes must multiply to the
    device count; a single -1 axis absorbs the remainder (numpy reshape
    convention)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axes:
        axes = {"data": n}
    names = list(axes.keys())
    sizes = list(axes.values())
    n_neg = sizes.count(-1)
    if n_neg > 1:
        raise ValueError("at most one -1 axis")
    if n_neg == 1:
        known = int(np.prod([s for s in sizes if s != -1])) or 1
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh axes {dict(zip(names, sizes))} != {n} devices")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))
