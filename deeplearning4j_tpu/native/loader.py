"""ctypes loader for the C++ native library, with on-demand g++ build.

Mirrors the reference's backend-by-availability seam (cuDNN helpers are
looked up reflectively and absent classes fall through to the built-in path,
`ConvolutionLayer.java:69-79`): if the shared library can be built/loaded,
hot host paths use it; otherwise every caller gets `None` and runs its
pure-Python fallback.
"""
from __future__ import annotations

import ctypes
import logging
import subprocess
import threading
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

_SRC = Path(__file__).parent / "src" / "dl4jtpu_native.cpp"
_SO = Path(__file__).parent / "_dl4jtpu_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           "-o", str(_SO), str(_SRC)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.info("native build unavailable (%s); using Python fallbacks", e)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed; using Python fallbacks:\n%s",
                       proc.stderr[-2000:])
        return False
    return True


def native_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first call; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError as e:
            logger.warning("native library load failed (%s)", e)
            return None
        lib.dl4j_csv_parse.restype = ctypes.c_void_p
        lib.dl4j_csv_parse.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_char]
        lib.dl4j_csv_ok.argtypes = [ctypes.c_void_p]
        lib.dl4j_csv_rows.restype = ctypes.c_int64
        lib.dl4j_csv_rows.argtypes = [ctypes.c_void_p]
        lib.dl4j_csv_cols.restype = ctypes.c_int64
        lib.dl4j_csv_cols.argtypes = [ctypes.c_void_p]
        lib.dl4j_csv_data.restype = ctypes.POINTER(ctypes.c_double)
        lib.dl4j_csv_data.argtypes = [ctypes.c_void_p]
        lib.dl4j_csv_free.argtypes = [ctypes.c_void_p]
        lib.dl4j_wc_create.restype = ctypes.c_void_p
        lib.dl4j_wc_add_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.dl4j_wc_total.restype = ctypes.c_int64
        lib.dl4j_wc_total.argtypes = [ctypes.c_void_p]
        lib.dl4j_wc_unique.restype = ctypes.c_int64
        lib.dl4j_wc_unique.argtypes = [ctypes.c_void_p]
        lib.dl4j_wc_serialize.restype = ctypes.c_int64
        lib.dl4j_wc_serialize.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_char_p)]
        lib.dl4j_buf_free.argtypes = [ctypes.c_char_p]
        lib.dl4j_wc_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return native_lib() is not None


def csv_parse_numeric(path, skip_lines: int = 0,
                      delimiter: str = ",") -> Optional[np.ndarray]:
    """Parse an all-numeric rectangular CSV into an (N, C) float64 array via
    the native parser. Returns None when the library is unavailable OR the
    file has string/ragged content — callers then run the Python path."""
    lib = native_lib()
    if lib is None or len(delimiter) != 1:
        return None
    h = lib.dl4j_csv_parse(str(path).encode(), int(skip_lines),
                           delimiter.encode())
    try:
        if not lib.dl4j_csv_ok(h):
            return None
        rows, cols = lib.dl4j_csv_rows(h), lib.dl4j_csv_cols(h)
        if rows == 0:
            return np.zeros((0, 0), np.float64)
        out = np.ctypeslib.as_array(lib.dl4j_csv_data(h),
                                    shape=(rows, cols)).copy()
        return out
    finally:
        lib.dl4j_csv_free(h)


def count_words(paths: List, lowercase: bool = True) -> Optional[Dict[str, int]]:
    """Count whitespace-separated tokens across text files via the native
    counter (vocab-construction hot loop). None if unavailable.

    Case folding happens HERE, over unique words only — the C tokenizer is
    byte-oriented and its tolower would be ASCII-only, which would diverge
    from the Python fallback's str.lower() on non-ASCII corpora."""
    lib = native_lib()
    if lib is None:
        return None
    h = lib.dl4j_wc_create()
    try:
        for p in paths:
            if not lib.dl4j_wc_add_file(h, str(p).encode(), 0):
                return None  # IO error: let caller fall back / raise its way
        buf = ctypes.c_char_p()
        n = lib.dl4j_wc_serialize(h, ctypes.byref(buf))
        if n < 0:
            return None
        try:
            raw = ctypes.string_at(buf, n)
        finally:
            lib.dl4j_buf_free(buf)
        counts: Dict[str, int] = {}
        # records are "word\tcount\n": split on \n ONLY — tokens may contain
        # other chars str.splitlines() treats as line breaks (\x1c, U+2028)
        for line in raw.decode("utf-8", errors="replace").split("\n"):
            if not line:
                continue
            word, _, c = line.rpartition("\t")
            if lowercase:
                word = word.lower()
            counts[word] = counts.get(word, 0) + int(c)
        return counts
    finally:
        lib.dl4j_wc_free(h)
