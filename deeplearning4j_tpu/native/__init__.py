"""Native (C++) runtime components, loaded via ctypes.

The reference's runtime around the JVM is native: libnd4j (C++) for tensor
storage/ops and DataVec's native-backed ETL (SURVEY §2.9). In this build the
device compute path is XLA; the native seam that remains hot on the HOST is
the input pipeline — parsing and staging batches fast enough to keep the
chip fed. Those pieces are implemented in C++ (`deeplearning4j_tpu/native/
src/`), compiled on first use with g++ into `_dl4jtpu_native.so`, and loaded
here through ctypes. Every entry point has a pure-Python fallback: the
framework works without a compiler; with one, the hot host paths go native.
"""
from deeplearning4j_tpu.native.loader import (
    count_words,
    csv_parse_numeric,
    native_available,
    native_lib,
)

__all__ = ["count_words", "csv_parse_numeric", "native_available", "native_lib"]
