// Native host-side runtime for deeplearning4j_tpu.
//
// The reference's hot host paths are native (libnd4j C++ buffers, DataVec
// ETL — SURVEY §2.9); here the device math is XLA's job, so the native seam
// is the input pipeline: CSV -> dense matrix parsing and corpus word
// counting (Word2Vec vocab construction, reference
// `wordstore/VocabConstructor.java` whose inner loop is the tokenize+count
// pass over the corpus).
//
// Plain C ABI (loaded via ctypes; pybind11 is not available in this image).
// Build: g++ -O3 -shared -fPIC -o _dl4jtpu_native.so dl4jtpu_native.cpp

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct CsvResult {
  std::vector<double> data;
  int64_t rows = 0;
  int64_t cols = 0;
  bool ok = false;  // false => non-numeric or ragged; caller falls back
};

// Read a whole file into memory. Returns false on IO error.
bool read_file(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (n < 0) { std::fclose(f); return false; }
  out->resize(static_cast<size_t>(n));
  size_t got = n ? std::fread(&(*out)[0], 1, static_cast<size_t>(n), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------- CSV parse
// Parses an all-numeric rectangular CSV into a dense row-major double
// matrix in one pass (strtod over a single in-memory buffer — no per-line
// allocation). If any token fails to parse or rows are ragged, ok=0 and the
// Python caller uses its general (string-aware) fallback.

void* dl4j_csv_parse(const char* path, int skip_lines, char delim) {
  auto* res = new CsvResult();
  std::string buf;
  if (!read_file(path, &buf)) return res;  // ok=false
  const char* p = buf.data();
  const char* end = p + buf.size();
  // skip header lines
  for (int s = 0; s < skip_lines && p < end; ++s) {
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
  int64_t cols = -1;
  std::vector<double> row;
  while (p < end) {
    const char* line_end = p;
    while (line_end < end && *line_end != '\n') ++line_end;
    // skip truly empty lines only (the Python path does the same);
    // whitespace-only lines are NOT numeric CSV -> bail to the fallback so
    // both paths agree on them
    bool empty = (p == line_end);
    bool ws_only = !empty;
    for (const char* q = p; q < line_end; ++q)
      if (!std::isspace(static_cast<unsigned char>(*q))) { ws_only = false; break; }
    if (ws_only) { delete res; return new CsvResult(); }
    if (!empty) {
      row.clear();
      const char* q = p;
      while (q <= line_end) {
        const char* tok_end = q;
        while (tok_end < line_end && *tok_end != delim) ++tok_end;
        // strtod accepts hex floats ("0x1F") that Python's float() rejects:
        // any x/X in the token means this is not plain-decimal CSV -> bail
        for (const char* r = q; r < tok_end; ++r)
          if (*r == 'x' || *r == 'X') { delete res; return new CsvResult(); }
        char* conv_end = nullptr;
        // strtod stops at delim/newline; ensure token non-empty
        double v = std::strtod(q, &conv_end);
        if (conv_end == q || conv_end > tok_end) { delete res; return new CsvResult(); }
        // only whitespace may remain between number and delimiter
        for (const char* r = conv_end; r < tok_end; ++r)
          if (!std::isspace(static_cast<unsigned char>(*r))) { delete res; return new CsvResult(); }
        row.push_back(v);
        if (tok_end >= line_end) break;
        q = tok_end + 1;
      }
      if (cols < 0) cols = static_cast<int64_t>(row.size());
      if (static_cast<int64_t>(row.size()) != cols) { delete res; res = new CsvResult(); return res; }
      res->data.insert(res->data.end(), row.begin(), row.end());
      ++res->rows;
    }
    p = (line_end < end) ? line_end + 1 : end;
  }
  res->cols = cols < 0 ? 0 : cols;
  res->ok = true;
  return res;
}

int dl4j_csv_ok(void* h) { return static_cast<CsvResult*>(h)->ok ? 1 : 0; }
int64_t dl4j_csv_rows(void* h) { return static_cast<CsvResult*>(h)->rows; }
int64_t dl4j_csv_cols(void* h) { return static_cast<CsvResult*>(h)->cols; }
const double* dl4j_csv_data(void* h) {
  return static_cast<CsvResult*>(h)->data.data();
}
void dl4j_csv_free(void* h) { delete static_cast<CsvResult*>(h); }

// ------------------------------------------------------------ word counting
// Whitespace-tokenizing word counter over text files — the inner loop of
// vocab construction. Counts are serialized as "word\tcount\n" lines into a
// malloc'd buffer the Python side parses (strings can't cross a plain C ABI
// any cheaper without a real binding layer).

struct WordCounter {
  std::unordered_map<std::string, int64_t> counts;
  int64_t total = 0;
};

void* dl4j_wc_create() { return new WordCounter(); }

int dl4j_wc_add_file(void* h, const char* path, int lowercase) {
  auto* wc = static_cast<WordCounter*>(h);
  std::string buf;
  if (!read_file(path, &buf)) return 0;
  const char* p = buf.data();
  const char* end = p + buf.size();
  std::string word;
  while (p <= end) {
    char c = (p < end) ? *p : ' ';
    if (std::isspace(static_cast<unsigned char>(c))) {
      if (!word.empty()) {
        ++wc->counts[word];
        ++wc->total;
        word.clear();
      }
    } else {
      word.push_back(lowercase ? static_cast<char>(
          std::tolower(static_cast<unsigned char>(c))) : c);
    }
    ++p;
  }
  return 1;
}

int64_t dl4j_wc_total(void* h) { return static_cast<WordCounter*>(h)->total; }
int64_t dl4j_wc_unique(void* h) {
  return static_cast<int64_t>(static_cast<WordCounter*>(h)->counts.size());
}

// Serialize counts; caller frees with dl4j_buf_free. Returns byte length.
int64_t dl4j_wc_serialize(void* h, char** out) {
  auto* wc = static_cast<WordCounter*>(h);
  std::string s;
  s.reserve(wc->counts.size() * 16);
  char num[32];
  for (const auto& kv : wc->counts) {
    s.append(kv.first);
    std::snprintf(num, sizeof num, "\t%lld\n",
                  static_cast<long long>(kv.second));
    s.append(num);
  }
  *out = static_cast<char*>(std::malloc(s.size()));
  if (*out == nullptr) return -1;
  std::memcpy(*out, s.data(), s.size());
  return static_cast<int64_t>(s.size());
}

void dl4j_buf_free(char* p) { std::free(p); }
void dl4j_wc_free(void* h) { delete static_cast<WordCounter*>(h); }

}  // extern "C"
