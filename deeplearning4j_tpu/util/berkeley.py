"""Berkeley-NLP-style utility collection.

Reference: `deeplearning4j-nn/.../berkeley/` (SURVEY §2.1 "berkeley utils",
4,484 LoC vendored from the Berkeley NLP parser): `Counter`, `CounterMap`,
`PriorityQueue`, `Pair`, `SloppyMath`. Python's stdlib covers much of this;
what remains are the exact APIs the NLP stack leans on — kept as thin,
typed wrappers so call sites read like the reference.
"""
from __future__ import annotations

import heapq
import math
from collections import defaultdict
from typing import Dict, Generic, Iterator, List, Optional, Tuple, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class Counter(Generic[K], Dict[K, float]):
    """Map key → float count with argmax/normalize (reference
    `berkeley/Counter.java`)."""

    def increment_count(self, key: K, by: float = 1.0) -> None:
        self[key] = self.get(key, 0.0) + by

    def get_count(self, key: K) -> float:
        return self.get(key, 0.0)

    def total_count(self) -> float:
        return float(sum(self.values()))

    def arg_max(self) -> Optional[K]:
        return max(self, key=self.get) if self else None

    def max_count(self) -> float:
        return max(self.values()) if self else 0.0

    def normalize(self) -> None:
        total = self.total_count()
        if total == 0.0:
            return
        for k in self:
            self[k] /= total

    def sorted_keys(self) -> List[K]:
        """Keys by descending count."""
        return sorted(self, key=self.get, reverse=True)


class CounterMap(Generic[K, V]):
    """Two-level counter: key → (key2 → count) (reference
    `berkeley/CounterMap.java`)."""

    def __init__(self):
        self._map: Dict[K, Counter[V]] = defaultdict(Counter)

    def increment_count(self, key: K, key2: V, by: float = 1.0) -> None:
        self._map[key].increment_count(key2, by)

    def get_count(self, key: K, key2: V) -> float:
        return self._map[key].get_count(key2) if key in self._map else 0.0

    def get_counter(self, key: K) -> Counter[V]:
        return self._map[key]

    def keys(self):
        return self._map.keys()

    def total_count(self) -> float:
        return float(sum(c.total_count() for c in self._map.values()))

    def total_size(self) -> int:
        return sum(len(c) for c in self._map.values())

    def __contains__(self, key: K) -> bool:
        return key in self._map

    def __len__(self) -> int:
        return len(self._map)


class PriorityQueue(Generic[V]):
    """Max-priority queue with peek (reference `berkeley/PriorityQueue.java`
    — iteration order is descending priority)."""

    def __init__(self):
        self._heap: List[Tuple[float, int, V]] = []
        self._tie = 0

    def put(self, item: V, priority: float) -> None:
        # negate for max-heap; tie-breaker keeps insertion order stable
        heapq.heappush(self._heap, (-priority, self._tie, item))
        self._tie += 1

    def peek(self) -> V:
        if not self._heap:
            raise IndexError("peek on empty PriorityQueue")
        return self._heap[0][2]

    def get_priority(self) -> float:
        if not self._heap:
            raise IndexError("get_priority on empty PriorityQueue")
        return -self._heap[0][0]

    def next(self) -> V:
        if not self._heap:
            raise IndexError("next on empty PriorityQueue")
        return heapq.heappop(self._heap)[2]

    def is_empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)

    def __iter__(self) -> Iterator[V]:
        while self._heap:
            yield self.next()


class SloppyMath:
    """Numerically-forgiving math helpers (reference
    `berkeley/SloppyMath.java`)."""

    LOG_TOLERANCE = 30.0

    @staticmethod
    def log_add(log_x: float, log_y: float) -> float:
        """log(exp(x) + exp(y)) without overflow."""
        if log_x == -math.inf:
            return log_y
        if log_y == -math.inf:
            return log_x
        hi, lo = (log_x, log_y) if log_x >= log_y else (log_y, log_x)
        if hi - lo > SloppyMath.LOG_TOLERANCE:
            return hi
        return hi + math.log1p(math.exp(lo - hi))

    @staticmethod
    def log_subtract(log_x: float, log_y: float) -> float:
        """log(exp(x) - exp(y)); requires x >= y."""
        if log_y == -math.inf:
            return log_x
        if log_y > log_x:
            raise ValueError("log_subtract requires log_x >= log_y")
        if log_x == log_y:
            return -math.inf
        return log_x + math.log1p(-math.exp(log_y - log_x))

    @staticmethod
    def sigmoid(x: float) -> float:
        if x >= 0:
            return 1.0 / (1.0 + math.exp(-x))
        e = math.exp(x)
        return e / (1.0 + e)


Pair = Tuple  # reference `berkeley/Pair.java` — a plain tuple in Python
