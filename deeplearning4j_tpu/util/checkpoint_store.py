"""Durable checkpoint persistence: atomic commits, integrity manifests,
last-good fallback, and verified cloud transfer.

Every recovery path in this build (PR 1's `FaultTolerantTrainer`,
`EarlyStoppingDistributedTrainer(checkpoint_dir=...)`, the early-stopping
savers) bottoms out in a file write — and the reference's `ModelSerializer`
zip format (SURVEY §5) writes that file IN PLACE, so a preemption mid-save
destroys the exact artifact recovery depends on. Preemptible TPU fleets
make "killed mid-write" a routine event, not a corner case. This module is
the durability floor under the whole elastic-training tier:

- **atomic commit** — payloads are written to a temp name in the
  destination directory, flushed + fsynced, then published with
  `os.replace` (and a directory fsync), so a reader never observes a
  partial checkpoint: it sees the old artifact or the new one, nothing in
  between.
- **integrity manifest** — each checkpoint carries a sidecar
  `<name>.manifest.json` recording per-file size + SHA-256 + CRC32, the
  training step, wall-clock, and library version. `verify_manifest`
  re-hashes on load; any drift raises `CheckpointCorruptError`.
  The manifest is published AFTER its payload, so a crash between the two
  `os.replace` calls leaves an unverifiable (manifest-less) payload that
  the fallback loader skips — never a manifest vouching for bytes that
  don't exist.
- **last-good fallback** — `CheckpointStore` retains the newest
  `keep_last` checkpoints (GC removes payload + sidecar together) and
  `load_latest_verified` walks newest→oldest, skipping corrupt,
  truncated, or unverifiable entries, raising `CheckpointCorruptError`
  only when no checkpoint survives.
- **verified transfer** — `upload`/`download` move a checkpoint through
  any `cloud.storage.DataSetStorage` backend with the manifest's digests
  re-verified AFTER the transfer, retrying corrupt/failed transfers under
  the same bounded exponential-backoff discipline as PR 1's
  `RetryingParameterServerClient` (`retry_with_backoff` is the shared
  helper).

The chaos seam: `CheckpointStore(save_hooks=[...])` calls each hook at
named phases of a save (`pre_write`, `mid_write`, `pre_publish`,
`post_payload`). `parallel.fault_tolerance.CheckpointCrashInjector` uses
it to kill a save mid-write — the crash-during-save drill the chaos suite
runs end to end through `FaultTolerantTrainer`.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import re
import time
import zlib
from hashlib import sha256
from pathlib import Path
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")

MANIFEST_SUFFIX = ".manifest.json"
MANIFEST_FORMAT = "deeplearning4j_tpu/checkpoint-manifest/v1"
_HASH_CHUNK = 1 << 20


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification (truncated file, digest
    mismatch, missing manifest entry) — or, from
    `CheckpointStore.load_latest_verified`, NO retained checkpoint
    survived verification. Typed so recovery code can distinguish a
    damaged artifact from a bug in the restore path."""


# ---------------------------------------------------------------------------
# atomic publish primitives


def _fsync_dir(directory) -> None:
    """fsync a directory so a just-published rename survives power loss.
    Best-effort: some filesystems (and all of Windows) refuse O_RDONLY
    directory handles — atomicity still holds, only the rename's own
    durability ordering is weakened there."""
    try:
        fd = os.open(os.fspath(directory), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def fsync_file(path) -> None:
    with open(path, "rb+") as f:
        f.flush()
        os.fsync(f.fileno())


def fsync_dir(directory) -> None:
    """Public seam over `_fsync_dir`: fsync a directory so a just-created
    file (e.g. a fresh WAL segment in `serving.exactly_once`) survives
    power loss. Best-effort with the same caveats."""
    _fsync_dir(directory)


def crc32_hex(data: bytes) -> str:
    """CRC32 of `data` as 8 lowercase hex chars — the per-record checksum
    primitive shared by checkpoint manifests and the exactly-once request
    journal (`serving.exactly_once.RequestJournal`)."""
    return format(zlib.crc32(data) & 0xFFFFFFFF, "08x")


def _tmp_name(path: Path) -> Path:
    # same directory as the destination: os.replace must not cross a
    # filesystem boundary, and the unique suffix keeps concurrent savers
    # from clobbering each other's scratch
    return path.parent / f".{path.name}.tmp-{os.getpid()}-{time.monotonic_ns()}"


@contextlib.contextmanager
def atomic_write(path, fsync: bool = True):
    """Context manager yielding a temp path in `path`'s directory; on
    clean exit the temp file is fsynced and published over `path` with
    `os.replace`. On ANY exception the temp file is removed and the
    destination is untouched — a failed save can never damage the
    previous artifact."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = _tmp_name(path)
    try:
        yield tmp
        if fsync and tmp.exists():
            fsync_file(tmp)
        os.replace(tmp, path)
        _fsync_dir(path.parent)
    finally:
        with contextlib.suppress(OSError):
            tmp.unlink()


def atomic_write_bytes(path, data: bytes, fsync: bool = True) -> None:
    """Atomically publish `data` at `path` (temp + fsync + os.replace)."""
    with atomic_write(path, fsync=fsync) as tmp:
        tmp.write_bytes(data)


# ---------------------------------------------------------------------------
# integrity manifests


def file_digests(path) -> dict:
    """Size + SHA-256 + CRC32 of a file, streamed (checkpoints can exceed
    memory)."""
    h = sha256()
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_HASH_CHUNK)
            if not chunk:
                break
            h.update(chunk)
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return {"size": size, "sha256": h.hexdigest(),
            "crc32": format(crc & 0xFFFFFFFF, "08x")}


def _payload_files(path: Path) -> List[Tuple[str, Path]]:
    """(relative name, absolute path) pairs covered by a manifest: the
    file itself, or every regular file under a directory checkpoint
    (sharded/orbax layout) — sidecar manifests and temp scratch excluded."""
    if path.is_file():
        return [(path.name, path)]
    out = []
    for f in sorted(path.rglob("*")):
        if not f.is_file():
            continue
        name = f.relative_to(path).as_posix()
        # skip sidecars and our own temp scratch; dot-files generally are
        # payload (zarr's .zarray metadata lives in orbax trees)
        if name.endswith(MANIFEST_SUFFIX) \
                or (f.name.startswith(".") and ".tmp-" in f.name):
            continue
        out.append((name, f))
    return out


def build_manifest(path, step: Optional[int] = None, extra: dict = None) -> dict:
    """Manifest dict for a file or directory checkpoint: per-file
    size/SHA-256/CRC32 plus step, wall-clock, and library version."""
    from deeplearning4j_tpu import __version__

    path = Path(path)
    manifest = {
        "format": MANIFEST_FORMAT,
        "library_version": __version__,
        "wall_clock": time.time(),
        "step": step,
        "files": {name: file_digests(p) for name, p in _payload_files(path)},
    }
    if extra:
        manifest.update(extra)
    return manifest


def manifest_path_for(path) -> Path:
    path = Path(path)
    return path.parent / (path.name + MANIFEST_SUFFIX)


def write_manifest_for(path, step: Optional[int] = None,
                       extra: dict = None) -> Path:
    """Build and atomically publish the sidecar manifest for a checkpoint
    file or directory. Returns the manifest path."""
    mpath = manifest_path_for(path)
    manifest = build_manifest(path, step=step, extra=extra)
    atomic_write_bytes(mpath, json.dumps(manifest, indent=1).encode())
    return mpath


def load_manifest(path) -> dict:
    """Read the sidecar manifest for a checkpoint path. Raises
    `CheckpointCorruptError` when absent or unreadable (a payload without
    a vouching manifest is unverifiable, not trusted)."""
    mpath = manifest_path_for(path)
    try:
        manifest = json.loads(mpath.read_bytes().decode())
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"no integrity manifest for checkpoint {path} "
            f"(expected {mpath}) — save was interrupted before the "
            "manifest published, or the checkpoint predates manifests")
    except (ValueError, OSError) as e:
        raise CheckpointCorruptError(
            f"unreadable manifest {mpath}: {e}") from e
    if not isinstance(manifest, dict) or "files" not in manifest:
        raise CheckpointCorruptError(f"malformed manifest {mpath}")
    return manifest


def verify_manifest(path, manifest: Optional[dict] = None) -> dict:
    """Re-hash a checkpoint against its manifest; raises
    `CheckpointCorruptError` on any missing file, size drift, or digest
    mismatch. Returns the (verified) manifest."""
    path = Path(path)
    if manifest is None:
        manifest = load_manifest(path)
    for name, want in manifest["files"].items():
        f = path if path.is_file() and name == path.name else path / name
        if not f.is_file():
            raise CheckpointCorruptError(
                f"checkpoint {path}: manifest file {name!r} is missing")
        got = file_digests(f)
        if got["size"] != want["size"]:
            raise CheckpointCorruptError(
                f"checkpoint {path}: {name!r} is {got['size']} bytes, "
                f"manifest says {want['size']} (truncated/partial write)")
        if got["sha256"] != want.get("sha256", got["sha256"]) \
                or got["crc32"] != want.get("crc32", got["crc32"]):
            raise CheckpointCorruptError(
                f"checkpoint {path}: {name!r} digest mismatch "
                "(bit rot or tampering)")
    return manifest


# ---------------------------------------------------------------------------
# bounded-backoff retry (shared with cloud transfer; same discipline as
# PR 1's RetryingParameterServerClient)


_NON_RETRYABLE = (FileNotFoundError, PermissionError, IsADirectoryError,
                  NotADirectoryError)


def retry_with_backoff(fn: Callable, *, what: str = "operation",
                       max_retries: int = 3, backoff: float = 0.05,
                       backoff_multiplier: float = 2.0,
                       retryable: tuple = (ConnectionError, OSError,
                                           TimeoutError),
                       non_retryable: tuple = _NON_RETRYABLE):
    """Run `fn()`, retrying `retryable` failures after
    `backoff × backoff_multiplier^attempt` seconds, at most `max_retries`
    retries; exhaustion re-raises the last failure. Anything outside
    `retryable` is a bug, not a transient, and re-raises immediately —
    as do `non_retryable` types even when they subclass a retryable one
    (a missing key is missing, not flaky: FileNotFoundError is an
    OSError but no amount of backoff conjures the file)."""
    delay = backoff
    for attempt in range(max_retries + 1):
        try:
            return fn()
        except retryable as e:
            if isinstance(e, non_retryable) or attempt >= max_retries:
                raise
            logger.warning("%s failed (%s: %s); retry %d/%d in %.3fs",
                           what, type(e).__name__, e, attempt + 1,
                           max_retries, delay)
            time.sleep(delay)
            delay *= backoff_multiplier


# ---------------------------------------------------------------------------
# the store


class CheckpointStore:
    """A directory of durably-committed, manifest-verified checkpoints
    with keep-last-N retention and newest-verified-first restore.

    Layout (flat, compatible with `CheckpointListener`'s historical one):

        <dir>/checkpoint_<step>.zip                 payload
        <dir>/checkpoint_<step>.zip.manifest.json   integrity sidecar
        <dir>/latest                                newest-payload marker

    `save(step, writer)` hands `writer` a TEMP path to produce the payload
    at, then hashes it, and publishes payload → manifest → marker in that
    order, each with `os.replace`. Crash anywhere and the directory holds
    only whole artifacts; crash between payload and manifest and the
    orphan payload is skipped by `load_latest_verified`, excluded from
    retention counting, and overwritten by the next save of that step.

    `save_hooks`: callables `hook(phase, step, path)` fired at
    `pre_write` / `mid_write` / `pre_publish` / `post_payload` — the
    chaos-injection seam (`CheckpointCrashInjector`)."""

    def __init__(self, directory, keep_last: int = 3,
                 prefix: str = "checkpoint_", suffix: str = ".zip",
                 save_hooks=()):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.keep_last = max(1, keep_last)
        self.prefix = prefix
        self.suffix = suffix
        self.save_hooks = list(save_hooks)
        self._step_re = re.compile(
            re.escape(prefix) + r"(\d+)" + re.escape(suffix) + r"$")

    # -- layout ----------------------------------------------------------
    def path_for(self, step: int) -> Path:
        return self.directory / f"{self.prefix}{step}{self.suffix}"

    def steps(self) -> List[int]:
        """Published checkpoint steps, ascending (directory scan — the
        marker file is a convenience, never the source of truth)."""
        out = []
        for f in self.directory.iterdir():
            m = self._step_re.match(f.name)
            if m and f.is_file():
                out.append(int(m.group(1)))
        return sorted(out)

    def _hook(self, phase: str, step: int, path: Path) -> None:
        for hook in self.save_hooks:
            hook(phase, step, path)

    # -- commit ----------------------------------------------------------
    def save(self, step: int, writer: Callable[[Path], None]) -> Path:
        """Durably commit one checkpoint: `writer(tmp_path)` produces the
        payload at a temp name; the store fsyncs it, writes the manifest,
        and publishes both atomically. Returns the published payload
        path. On any failure (including an injected crash) the temp
        scratch is removed and previously published checkpoints are
        untouched."""
        final = self.path_for(step)
        tmp_payload = _tmp_name(final)
        mpath = manifest_path_for(final)
        tmp_manifest = _tmp_name(mpath)
        try:
            self._hook("pre_write", step, tmp_payload)
            writer(tmp_payload)
            self._hook("mid_write", step, tmp_payload)
            fsync_file(tmp_payload)
            manifest = build_manifest(tmp_payload, step=step)
            # the manifest vouches for the FINAL name, not the temp one
            manifest["files"] = {final.name: manifest["files"][tmp_payload.name]}
            tmp_manifest.write_bytes(json.dumps(manifest, indent=1).encode())
            fsync_file(tmp_manifest)
            self._hook("pre_publish", step, tmp_payload)
            os.replace(tmp_payload, final)
            self._hook("post_payload", step, final)
            os.replace(tmp_manifest, mpath)
            _fsync_dir(self.directory)
            atomic_write_bytes(self.directory / "latest",
                               final.name.encode())
            self.gc()
            return final
        finally:
            for t in (tmp_payload, tmp_manifest):
                with contextlib.suppress(OSError):
                    t.unlink()

    def save_bytes(self, step: int, data: bytes) -> Path:
        return self.save(step, lambda tmp: tmp.write_bytes(data))

    # -- verification / restore ------------------------------------------
    def verify(self, step: int) -> dict:
        """Verify one checkpoint's manifest; raises
        `CheckpointCorruptError`, returns the manifest."""
        return verify_manifest(self.path_for(step))

    def latest_verified(self) -> Optional[Tuple[int, Path]]:
        """(step, path) of the newest checkpoint that passes verification,
        or None when the store is empty. Corrupt/unverifiable entries are
        logged and skipped."""
        steps = self.steps()
        for step in reversed(steps):
            try:
                self.verify(step)
                return step, self.path_for(step)
            except CheckpointCorruptError as e:
                logger.warning("skipping checkpoint step %d: %s", step, e)
        if steps:
            raise CheckpointCorruptError(
                f"no verifiable checkpoint in {self.directory}: all "
                f"{len(steps)} retained entries failed integrity checks "
                f"(steps {steps})")
        return None

    def load_latest_verified(self, loader: Callable[[Path], object]):
        """Restore from the newest checkpoint that (a) passes manifest
        verification and (b) `loader(path)` accepts; walks backwards over
        both kinds of damage. Returns `(loader_result, step)`. Raises
        `CheckpointCorruptError` when checkpoints exist but NONE survive,
        and `FileNotFoundError` when the store is empty."""
        steps = self.steps()
        if not steps:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        failures = []
        for step in reversed(steps):
            path = self.path_for(step)
            try:
                self.verify(step)
                return loader(path), step
            except CheckpointCorruptError as e:
                # loader may raise it too (e.g. a zip whose deflate
                # stream is damaged in a CRC32/SHA-colliding way the
                # manifest can't catch — or a legacy manifest-less file)
                logger.warning("skipping checkpoint step %d: %s", step, e)
                failures.append((step, str(e)))
        raise CheckpointCorruptError(
            f"no loadable checkpoint in {self.directory}: "
            + "; ".join(f"step {s}: {msg}" for s, msg in failures))

    # -- retention -------------------------------------------------------
    def gc(self) -> List[Path]:
        """Drop all but the newest `keep_last` VERIFIABLE checkpoints
        (payload AND sidecar together), plus orphaned sidecars/markers
        and stale temp scratch. Manifest-less payloads (crashed saves,
        legacy writers) never count toward retention — an unrestorable
        orphan must not evict a restorable checkpoint — and are left in
        place (the next save of that step overwrites them; the legacy
        marker path may still read them). Returns removed payload
        paths."""
        removed = []
        steps = [s for s in self.steps()
                 if manifest_path_for(self.path_for(s)).exists()]
        for step in steps[:-self.keep_last] if len(steps) > self.keep_last \
                else []:
            p = self.path_for(step)
            for f in (p, manifest_path_for(p)):
                with contextlib.suppress(OSError):
                    f.unlink()
            removed.append(p)
        for f in self.directory.iterdir():
            # manifest whose payload is gone, or abandoned temp scratch
            if f.name.endswith(MANIFEST_SUFFIX):
                payload = f.with_name(f.name[:-len(MANIFEST_SUFFIX)])
                if not payload.exists():
                    with contextlib.suppress(OSError):
                        f.unlink()
            elif f.name.startswith(".") and ".tmp-" in f.name:
                with contextlib.suppress(OSError):
                    f.unlink()
        return removed

    # -- verified cloud transfer -----------------------------------------
    # one verified-transfer implementation: both directions ride
    # `cloud.storage.RetryingStorage` (read-back digest verify on put,
    # expected-digest verify on get, bounded backoff retry on both)
    def _transfer_keys(self, key_prefix: str, name: str) -> Tuple[str, str]:
        base = f"{key_prefix.rstrip('/')}/{name}" if key_prefix else name
        return base, base + MANIFEST_SUFFIX

    @staticmethod
    def _retrying(storage, max_retries: int, backoff: float):
        from deeplearning4j_tpu.cloud.storage import RetryingStorage

        if isinstance(storage, RetryingStorage):
            return storage
        return RetryingStorage(storage, max_retries=max_retries,
                               backoff=backoff)

    def upload(self, storage, key_prefix: str = "",
               step: Optional[int] = None, max_retries: int = 3,
               backoff: float = 0.05) -> str:
        """Upload one checkpoint (newest verified when `step` is None)
        through a `DataSetStorage` backend with the payload's digest
        re-verified after the transfer (read-back compare) — a transfer
        that corrupts bytes in flight is retried, and exhaustion raises
        `CheckpointCorruptError`. Returns the payload key."""
        if step is None:
            latest = self.latest_verified()
            if latest is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}")
            step, path = latest
        else:
            path = self.path_for(step)
        self.verify(step)
        key, mkey = self._transfer_keys(key_prefix, path.name)
        st = self._retrying(storage, max_retries, backoff)
        # payload strictly before manifest, mirroring the local publish
        # order: a crash between the two leaves an unverifiable remote
        # orphan, never a manifest vouching for missing bytes
        st.put_bytes(key, path.read_bytes())
        st.put_bytes(mkey, manifest_path_for(path).read_bytes())
        return key

    def download(self, storage, key_prefix: str = "",
                 step: Optional[int] = None, max_retries: int = 3,
                 backoff: float = 0.05) -> Path:
        """Fetch a checkpoint (newest remote step when `step` is None)
        from a `DataSetStorage` backend into this store, re-verifying the
        manifest digests after transfer and retrying a corrupt download.
        The local copy is committed atomically (payload before manifest).
        Returns the local payload path."""
        st = self._retrying(storage, max_retries, backoff)
        if step is None:
            pref = f"{key_prefix.rstrip('/')}/" if key_prefix else ""
            remote_steps = []
            for k in st.list_keys(pref + self.prefix):
                m = self._step_re.match(k[len(pref):])
                if m:
                    remote_steps.append(int(m.group(1)))
            if not remote_steps:
                raise FileNotFoundError(
                    f"no remote checkpoints under {key_prefix!r}")
            step = max(remote_steps)
        final = self.path_for(step)
        key, mkey = self._transfer_keys(key_prefix, final.name)
        manifest_bytes = st.get_bytes(mkey)
        manifest = json.loads(manifest_bytes.decode())
        want = manifest["files"][final.name]
        data = st.get_bytes(key, expected_sha256=want["sha256"])
        if len(data) != want["size"]:
            raise CheckpointCorruptError(
                f"download of {key} corrupted in transit "
                f"({len(data)} bytes, manifest says {want['size']})")
        atomic_write_bytes(final, data)
        atomic_write_bytes(manifest_path_for(final), manifest_bytes)
        self.verify(step)
        self.gc()
        return final
