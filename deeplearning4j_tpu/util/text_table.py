"""Plain-text column tables for the summary() surfaces."""
from typing import List, Sequence


def format_table(rows: Sequence[Sequence[str]], footer: str) -> str:
    """Left-aligned columns from (header, *data) rows, a rule under the
    header, and a footer line. Shared by MultiLayerNetwork.summary() and
    ComputationGraph.summary() so their formatting cannot diverge."""
    ncols = len(rows[0])
    widths = [max(len(r[c]) for r in rows) for c in range(ncols)]
    lines: List[str] = ["  ".join(f"{r[c]:<{widths[c]}}" for c in range(ncols))
                        for r in rows]
    lines.insert(1, "-" * len(lines[0]))
    lines.append(footer)
    return "\n".join(lines)
