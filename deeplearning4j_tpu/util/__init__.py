"""Utilities: conv shape math, serialization, durable checkpoint store
(atomic commits + integrity manifests + last-good fallback,
`checkpoint_store.py`), time-series helpers."""
