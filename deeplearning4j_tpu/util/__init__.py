"""Utilities: conv shape math, serialization, time-series helpers."""
