"""Model checkpoint save/restore.

Reference: `deeplearning4j-nn/.../util/ModelSerializer.java:82` — a zip
containing `configuration.json` (:93), `coefficients.bin` (:98, flat param
vector), `updaterState.bin` (:120-134, flat optimizer-state view),
`normalizer.bin` (:43). Same layout here (npy instead of Nd4j binary), plus
`layerState.npy` for batch-norm running statistics and `meta.json`
(iteration/epoch/model type) so resume continues schedules and Adam moments
exactly — the key round-trip property called out in SURVEY §5
(checkpoint/resume). Works for both MultiLayerNetwork and ComputationGraph
(reference `restoreMultiLayerNetwork` / `restoreComputationGraph`).

Durability: `write_model` commits through `util/checkpoint_store.atomic_write`
(temp file + fsync + `os.replace`) — the reference's `ModelSerializer`
truncates the destination in place, so a crash mid-save destroys the very
artifact recovery needs; here a reader sees the old zip or the new one,
never a partial. Restores translate zip-level damage (truncation, bad
CRC, missing entries) into a typed `CheckpointCorruptError` so recovery
code can skip to an older checkpoint instead of dying on a raw
`BadZipFile`/`KeyError`.
"""
from __future__ import annotations

import contextlib
import io
import json
import struct
import zipfile
import zlib
from pathlib import Path
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.util.checkpoint_store import (
    CheckpointCorruptError,
    atomic_write,
)

CONFIG_JSON = "configuration.json"
COEFFICIENTS = "coefficients.npy"
UPDATER_STATE = "updaterState.npy"
LAYER_STATE = "layerState.npy"
NORMALIZER = "normalizer.bin"
META_JSON = "meta.json"


def write_model(net, path: Union[str, Path], save_updater: bool = True,
                normalizer=None, atomic: bool = True) -> None:
    """Save a MultiLayerNetwork or ComputationGraph (reference
    `ModelSerializer.writeModel`; `normalizer` → `normalizer.bin`:43).

    `atomic=False` writes the zip straight to `path` — ONLY for callers
    that already own an atomic commit (e.g. a `CheckpointStore.save`
    writer targeting the store's temp scratch), where a second
    temp+fsync+replace pass would double the per-save fsync cost for no
    added safety."""
    net._ensure_init()
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    model_type = type(net).__name__
    # atomic commit: build the zip at a temp name, fsync, then os.replace
    # over the destination — a crash mid-save leaves the previous
    # checkpoint intact instead of a truncated zip
    with (atomic_write(path) if atomic
          else contextlib.nullcontext(path)) as tmp:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr(CONFIG_JSON, net.conf.to_json())
            z.writestr(COEFFICIENTS, _np_bytes(net.params()))
            if save_updater and net._upd_state is not None:
                flat, _ = ravel_pytree(net._upd_state)
                z.writestr(UPDATER_STATE, _np_bytes(np.asarray(flat)))
            if net._layer_state is not None:
                flat, _ = ravel_pytree(net._layer_state)
                z.writestr(LAYER_STATE, _np_bytes(np.asarray(flat)))
            if normalizer is not None:
                z.writestr(NORMALIZER, normalizer.to_bytes())
            z.writestr(META_JSON, json.dumps({
                "iteration": net.iteration,
                "epoch": net.epoch,
                "dtype": str(np.dtype(net.dtype)),
                "model_type": model_type,
                "format": "deeplearning4j_tpu/model/v1",
            }))


_ZIP_DAMAGE = (zipfile.BadZipFile, KeyError, EOFError, zlib.error,
               struct.error)


class _corrupt_as_typed:
    """Translate zip-level damage (truncated file, bad CRC, missing
    member) into `CheckpointCorruptError` — deliberate ValueErrors from
    shape/type validation pass through untouched."""

    def __init__(self, path):
        self.path = path

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and isinstance(exc, _ZIP_DAMAGE):
            raise CheckpointCorruptError(
                f"checkpoint {self.path} is corrupt or truncated "
                f"({type(exc).__name__}: {exc})") from exc
        return False


def _restore(path, load_updater: bool, expect_type: Optional[str]):
    with _corrupt_as_typed(path), zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read(META_JSON).decode())
        model_type = meta.get("model_type", "MultiLayerNetwork")
        if expect_type is not None and model_type != expect_type:
            raise ValueError(
                f"checkpoint holds a {model_type}, not a {expect_type} — "
                f"use restore_{'computation_graph' if model_type == 'ComputationGraph' else 'multi_layer_network'}()")
        dtype = jnp.dtype(meta.get("dtype", "float32"))
        cfg_json = z.read(CONFIG_JSON).decode()
        if model_type == "ComputationGraph":
            from deeplearning4j_tpu.nn.conf.computation_graph_configuration import (
                ComputationGraphConfiguration,
            )
            from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph

            net = ComputationGraph(ComputationGraphConfiguration.from_json(cfg_json),
                                   dtype=dtype)
        else:
            from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
                MultiLayerConfiguration,
            )
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            net = MultiLayerNetwork(MultiLayerConfiguration.from_json(cfg_json),
                                    dtype=dtype)
        net.init()
        net.set_params(_np_load(z.read(COEFFICIENTS)))
        if load_updater and UPDATER_STATE in z.namelist():
            flat_now, unravel = ravel_pytree(net._upd_state)
            saved = _np_load(z.read(UPDATER_STATE))
            if saved.shape != flat_now.shape:
                raise ValueError(
                    f"checkpoint updater state has {saved.shape[0]} values "
                    f"but the rebuilt network expects {flat_now.shape[0]} — "
                    "corrupted checkpoint or config drift (pass "
                    "load_updater=False to restore params only)")
            net._upd_state = unravel(jnp.asarray(saved))
        if LAYER_STATE in z.namelist():
            flat_now, unravel = ravel_pytree(net._layer_state)
            saved = _np_load(z.read(LAYER_STATE))
            if flat_now.size:
                if saved.shape != flat_now.shape:
                    raise ValueError(
                        f"checkpoint layer state has {saved.shape[0]} values "
                        f"but the rebuilt network expects {flat_now.shape[0]}")
                net._layer_state = unravel(jnp.asarray(saved))
        net.iteration = meta.get("iteration", 0)
        net.epoch = meta.get("epoch", 0)
    return net


def restore_multi_layer_network(path: Union[str, Path], load_updater: bool = True):
    """Restore (reference `ModelSerializer.restoreMultiLayerNetwork`)."""
    return _restore(path, load_updater, "MultiLayerNetwork")


def restore_computation_graph(path: Union[str, Path], load_updater: bool = True):
    """Restore (reference `ModelSerializer.restoreComputationGraph`)."""
    return _restore(path, load_updater, "ComputationGraph")


def restore_model(path: Union[str, Path], load_updater: bool = True):
    """Type-sniffing restore (reference `util/ModelGuesser`)."""
    return _restore(path, load_updater, None)


def restore_normalizer(path: Union[str, Path]):
    """Read `normalizer.bin` back (reference
    `ModelSerializer.restoreNormalizerFromFile`); None if absent."""
    from deeplearning4j_tpu.datasets.normalizers import DataNormalization

    with _corrupt_as_typed(path), zipfile.ZipFile(path, "r") as z:
        if NORMALIZER not in z.namelist():
            return None
        return DataNormalization.from_bytes(z.read(NORMALIZER))


def _np_bytes(a: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, a)
    return buf.getvalue()


def _np_load(b: bytes) -> np.ndarray:
    return np.load(io.BytesIO(b))
