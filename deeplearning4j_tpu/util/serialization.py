"""Model checkpoint save/restore.

Reference: `deeplearning4j-nn/.../util/ModelSerializer.java:82` — a zip
containing `configuration.json` (:93), `coefficients.bin` (:98, flat param
vector), `updaterState.bin` (:120-134, flat optimizer-state view),
`normalizer.bin` (:43). Same layout here (npy instead of Nd4j binary), plus
`layerState.npy` for batch-norm running statistics and `meta.json`
(iteration/epoch/model type) so resume continues schedules and Adam moments
exactly — the key round-trip property called out in SURVEY §5
(checkpoint/resume). Works for both MultiLayerNetwork and ComputationGraph
(reference `restoreMultiLayerNetwork` / `restoreComputationGraph`).
"""
from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

CONFIG_JSON = "configuration.json"
COEFFICIENTS = "coefficients.npy"
UPDATER_STATE = "updaterState.npy"
LAYER_STATE = "layerState.npy"
NORMALIZER = "normalizer.bin"
META_JSON = "meta.json"


def write_model(net, path: Union[str, Path], save_updater: bool = True,
                normalizer=None) -> None:
    """Save a MultiLayerNetwork or ComputationGraph (reference
    `ModelSerializer.writeModel`; `normalizer` → `normalizer.bin`:43)."""
    net._ensure_init()
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    model_type = type(net).__name__
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(CONFIG_JSON, net.conf.to_json())
        z.writestr(COEFFICIENTS, _np_bytes(net.params()))
        if save_updater and net._upd_state is not None:
            flat, _ = ravel_pytree(net._upd_state)
            z.writestr(UPDATER_STATE, _np_bytes(np.asarray(flat)))
        if net._layer_state is not None:
            flat, _ = ravel_pytree(net._layer_state)
            z.writestr(LAYER_STATE, _np_bytes(np.asarray(flat)))
        if normalizer is not None:
            z.writestr(NORMALIZER, normalizer.to_bytes())
        z.writestr(META_JSON, json.dumps({
            "iteration": net.iteration,
            "epoch": net.epoch,
            "dtype": str(np.dtype(net.dtype)),
            "model_type": model_type,
            "format": "deeplearning4j_tpu/model/v1",
        }))


def _restore(path, load_updater: bool, expect_type: Optional[str]):
    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read(META_JSON).decode())
        model_type = meta.get("model_type", "MultiLayerNetwork")
        if expect_type is not None and model_type != expect_type:
            raise ValueError(
                f"checkpoint holds a {model_type}, not a {expect_type} — "
                f"use restore_{'computation_graph' if model_type == 'ComputationGraph' else 'multi_layer_network'}()")
        dtype = jnp.dtype(meta.get("dtype", "float32"))
        cfg_json = z.read(CONFIG_JSON).decode()
        if model_type == "ComputationGraph":
            from deeplearning4j_tpu.nn.conf.computation_graph_configuration import (
                ComputationGraphConfiguration,
            )
            from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph

            net = ComputationGraph(ComputationGraphConfiguration.from_json(cfg_json),
                                   dtype=dtype)
        else:
            from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
                MultiLayerConfiguration,
            )
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            net = MultiLayerNetwork(MultiLayerConfiguration.from_json(cfg_json),
                                    dtype=dtype)
        net.init()
        net.set_params(_np_load(z.read(COEFFICIENTS)))
        if load_updater and UPDATER_STATE in z.namelist():
            flat_now, unravel = ravel_pytree(net._upd_state)
            saved = _np_load(z.read(UPDATER_STATE))
            if saved.shape != flat_now.shape:
                raise ValueError(
                    f"checkpoint updater state has {saved.shape[0]} values "
                    f"but the rebuilt network expects {flat_now.shape[0]} — "
                    "corrupted checkpoint or config drift (pass "
                    "load_updater=False to restore params only)")
            net._upd_state = unravel(jnp.asarray(saved))
        if LAYER_STATE in z.namelist():
            flat_now, unravel = ravel_pytree(net._layer_state)
            saved = _np_load(z.read(LAYER_STATE))
            if flat_now.size:
                if saved.shape != flat_now.shape:
                    raise ValueError(
                        f"checkpoint layer state has {saved.shape[0]} values "
                        f"but the rebuilt network expects {flat_now.shape[0]}")
                net._layer_state = unravel(jnp.asarray(saved))
        net.iteration = meta.get("iteration", 0)
        net.epoch = meta.get("epoch", 0)
    return net


def restore_multi_layer_network(path: Union[str, Path], load_updater: bool = True):
    """Restore (reference `ModelSerializer.restoreMultiLayerNetwork`)."""
    return _restore(path, load_updater, "MultiLayerNetwork")


def restore_computation_graph(path: Union[str, Path], load_updater: bool = True):
    """Restore (reference `ModelSerializer.restoreComputationGraph`)."""
    return _restore(path, load_updater, "ComputationGraph")


def restore_model(path: Union[str, Path], load_updater: bool = True):
    """Type-sniffing restore (reference `util/ModelGuesser`)."""
    return _restore(path, load_updater, None)


def restore_normalizer(path: Union[str, Path]):
    """Read `normalizer.bin` back (reference
    `ModelSerializer.restoreNormalizerFromFile`); None if absent."""
    from deeplearning4j_tpu.datasets.normalizers import DataNormalization

    with zipfile.ZipFile(path, "r") as z:
        if NORMALIZER not in z.namelist():
            return None
        return DataNormalization.from_bytes(z.read(NORMALIZER))


def _np_bytes(a: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, a)
    return buf.getvalue()


def _np_load(b: bytes) -> np.ndarray:
    return np.load(io.BytesIO(b))
