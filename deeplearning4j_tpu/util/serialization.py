"""Model checkpoint save/restore.

Reference: `deeplearning4j-nn/.../util/ModelSerializer.java:82` — a zip
containing `configuration.json` (:93), `coefficients.bin` (:98, flat param
vector), `updaterState.bin` (:120-134, flat optimizer-state view),
`normalizer.bin`. Same layout here (npy instead of Nd4j binary), plus
`layerState.npy` for batch-norm running statistics and `meta.json`
(iteration/epoch) so resume continues schedules and Adam moments exactly —
the key round-trip property called out in SURVEY §5 (checkpoint/resume).
"""
from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path
from typing import Union

import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

CONFIG_JSON = "configuration.json"
COEFFICIENTS = "coefficients.npy"
UPDATER_STATE = "updaterState.npy"
LAYER_STATE = "layerState.npy"
META_JSON = "meta.json"


def write_model(net, path: Union[str, Path], save_updater: bool = True) -> None:
    """Save a MultiLayerNetwork (reference `ModelSerializer.writeModel`)."""
    net._ensure_init()
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(CONFIG_JSON, net.conf.to_json())
        z.writestr(COEFFICIENTS, _np_bytes(net.params()))
        if save_updater and net._upd_state is not None:
            flat, _ = ravel_pytree(net._upd_state)
            z.writestr(UPDATER_STATE, _np_bytes(np.asarray(flat)))
        if net._layer_state is not None:
            flat, _ = ravel_pytree(net._layer_state)
            z.writestr(LAYER_STATE, _np_bytes(np.asarray(flat)))
        z.writestr(META_JSON, json.dumps({
            "iteration": net.iteration,
            "epoch": net.epoch,
            "dtype": str(np.dtype(net.dtype)),
            "format": "deeplearning4j_tpu/model/v1",
        }))


def restore_multi_layer_network(path: Union[str, Path], load_updater: bool = True):
    """Restore (reference `ModelSerializer.restoreMultiLayerNetwork`)."""
    from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
        MultiLayerConfiguration,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path, "r") as z:
        conf = MultiLayerConfiguration.from_json(z.read(CONFIG_JSON).decode())
        meta = json.loads(z.read(META_JSON).decode())
        dtype = jnp.dtype(meta.get("dtype", "float32"))
        net = MultiLayerNetwork(conf, dtype=dtype)
        net.init()
        net.set_params(_np_load(z.read(COEFFICIENTS)))
        if load_updater and UPDATER_STATE in z.namelist():
            flat_now, unravel = ravel_pytree(net._upd_state)
            saved = _np_load(z.read(UPDATER_STATE))
            if saved.shape != flat_now.shape:
                raise ValueError(
                    f"checkpoint updater state has {saved.shape[0]} values "
                    f"but the rebuilt network expects {flat_now.shape[0]} — "
                    "corrupted checkpoint or config drift (pass "
                    "load_updater=False to restore params only)")
            net._upd_state = unravel(jnp.asarray(saved))
        if LAYER_STATE in z.namelist():
            flat_now, unravel = ravel_pytree(net._layer_state)
            saved = _np_load(z.read(LAYER_STATE))
            if flat_now.size:
                if saved.shape != flat_now.shape:
                    raise ValueError(
                        f"checkpoint layer state has {saved.shape[0]} values "
                        f"but the rebuilt network expects {flat_now.shape[0]}")
                net._layer_state = unravel(jnp.asarray(saved))
        net.iteration = meta.get("iteration", 0)
        net.epoch = meta.get("epoch", 0)
    return net


def _np_bytes(a: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, a)
    return buf.getvalue()


def _np_load(b: bytes) -> np.ndarray:
    return np.load(io.BytesIO(b))
