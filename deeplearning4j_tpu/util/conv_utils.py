"""Convolution/pooling shape math.

Reference: `deeplearning4j-nn/.../util/ConvolutionUtils.java`
(`getOutputSize`, Same-mode padding) and `nn/conf/ConvolutionMode.java`:
- Strict:   out = (in - k + 2p) / s + 1, must divide exactly (else error)
- Truncate: out = floor((in - k + 2p) / s) + 1
- Same:     out = ceil(in / s), with asymmetric implicit padding
"""
from __future__ import annotations

import enum
import math
from typing import Sequence, Tuple


class ConvolutionMode(str, enum.Enum):
    STRICT = "strict"
    TRUNCATE = "truncate"
    SAME = "same"


class PoolingType(str, enum.Enum):
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


def output_size_1d(in_size: int, kernel: int, stride: int, padding: int,
                   mode: ConvolutionMode, dilation: int = 1) -> int:
    eff_k = kernel + (kernel - 1) * (dilation - 1)
    if mode == ConvolutionMode.SAME:
        return int(math.ceil(in_size / stride))
    num = in_size - eff_k + 2 * padding
    if mode == ConvolutionMode.STRICT:
        if num % stride != 0:
            raise ValueError(
                f"ConvolutionMode.Strict: (in={in_size} - k={eff_k} + 2*p={padding}) "
                f"= {num} not divisible by stride {stride} "
                "(reference ConvolutionUtils.getOutputSize error path)")
        return num // stride + 1
    return num // stride + 1  # Truncate: floor


def same_padding_1d(in_size: int, kernel: int, stride: int, dilation: int = 1) -> Tuple[int, int]:
    """Asymmetric (lo, hi) padding for ConvolutionMode.Same — matches XLA's
    'SAME' semantics and the reference's Same-mode implicit padding."""
    eff_k = kernel + (kernel - 1) * (dilation - 1)
    out = int(math.ceil(in_size / stride))
    total = max(0, (out - 1) * stride + eff_k - in_size)
    lo = total // 2
    return lo, total - lo


def conv_output_hw(
    hw: Tuple[int, int],
    kernel: Tuple[int, int],
    stride: Tuple[int, int],
    padding: Tuple[int, int],
    mode: ConvolutionMode,
    dilation: Tuple[int, int] = (1, 1),
) -> Tuple[int, int]:
    return (
        output_size_1d(hw[0], kernel[0], stride[0], padding[0], mode, dilation[0]),
        output_size_1d(hw[1], kernel[1], stride[1], padding[1], mode, dilation[1]),
    )


def explicit_padding(
    hw: Tuple[int, int],
    kernel: Tuple[int, int],
    stride: Tuple[int, int],
    padding: Tuple[int, int],
    mode: ConvolutionMode,
    dilation: Tuple[int, int] = (1, 1),
) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """((top, bottom), (left, right)) padding to hand to
    lax.conv_general_dilated / lax.reduce_window."""
    if mode == ConvolutionMode.SAME:
        return (
            same_padding_1d(hw[0], kernel[0], stride[0], dilation[0]),
            same_padding_1d(hw[1], kernel[1], stride[1], dilation[1]),
        )
    return ((padding[0], padding[0]), (padding[1], padding[1]))
