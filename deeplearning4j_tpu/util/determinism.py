"""Determinism checking.

Reference (SURVEY §5 "Race detection / sanitizers"): none — the JVM
reference relies on `synchronized` and blocking queues. The TPU-build
analogue of a race detector is a DETERMINISM CHECK: all device math is
compiled and seeded, so two same-seed runs must produce bit-identical
parameters; any divergence indicates nondeterminism sneaking in (host
threading feeding batches out of order, un-seeded randomness,
non-reproducible reductions).
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np


def assert_deterministic(net_factory: Callable[[], object],
                        batches: Sequence, epochs: int = 1,
                        atol: float = 0.0) -> None:
    """Train two independently constructed nets on the same batches and
    assert parameter equality (bit-exact by default).

    net_factory: () -> initialized network (fresh params each call, same
    seed via its configuration); batches: list of DataSets.
    """
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    runs = []
    for _ in range(2):
        net = net_factory()
        net.fit(ListDataSetIterator(list(batches)), epochs=epochs)
        runs.append(net.params())
    a, b = runs
    if not np.isfinite(a).all():
        raise AssertionError(
            "training diverged (non-finite parameters) — determinism "
            "cannot be assessed; lower the learning rate first")
    if atol == 0.0:
        if not np.array_equal(a, b, equal_nan=True):
            diff = np.abs(a - b)
            mism = int((~np.isclose(a, b, rtol=0, atol=0)).sum())
            raise AssertionError(
                f"nondeterministic training: params differ at "
                f"{mism}/{a.size} positions "
                f"(max |diff| = {np.nanmax(diff):.3e})")
    else:
        np.testing.assert_allclose(a, b, atol=atol)
