"""Concurrency debug helpers backing the ``# guarded by:`` contracts
that `tools/graftlint` checks statically.

The static checker proves *lexical* discipline (writes to an annotated
field happen inside ``with <lock>:``); `assert_owned` is the runtime
half, catching the cases the linter deliberately leaves to convention —
``*_locked`` methods and ``# graftlint: holds <lock>`` markers, where
the CALLER promises to hold the lock. Guarded classes call it at the
top of such methods; under tests (or with
``DL4J_TPU_CONCURRENCY_ASSERTS=1``) a broken promise raises instead of
corrupting state silently. In production the check is a no-op.
"""
from __future__ import annotations

import os

__all__ = ["assert_owned", "asserts_enabled"]


def asserts_enabled() -> bool:
    """True when ownership assertions should run: under pytest (it
    exports ``PYTEST_CURRENT_TEST`` per test) or when explicitly armed
    via ``DL4J_TPU_CONCURRENCY_ASSERTS``."""
    return ("PYTEST_CURRENT_TEST" in os.environ
            or bool(os.environ.get("DL4J_TPU_CONCURRENCY_ASSERTS")))


def assert_owned(lock, what: str = "shared state") -> None:
    """Assert the calling thread holds `lock`.

    No-op when `lock` is None (an externally-synchronized object whose
    guard was never bound) or when assertions are disabled. Uses the
    lock's ``_is_owned()`` when available (Condition/RLock — a true
    per-thread ownership check); plain ``threading.Lock`` only exposes
    ``locked()``, a weaker held-by-somebody check, which still catches
    the common bug of calling a ``*_locked`` method with no lock held
    at all.
    """
    if lock is None or not asserts_enabled():
        return
    is_owned = getattr(lock, "_is_owned", None)
    if callable(is_owned):
        held = is_owned()
    else:
        locked = getattr(lock, "locked", None)
        held = locked() if callable(locked) else True
    if not held:
        raise AssertionError(
            f"{what} requires holding {lock!r}, but the calling thread "
            f"does not own it (see the `# guarded by:` annotation and "
            f"docs/static_analysis.md)")
