"""Sharded (multi-chip) checkpointing for distributed training state.

The reference's checkpoint format (`util/ModelSerializer.java:82` — one
zip with the FULL flat parameter vector) assumes the model fits on, and is
gathered to, a single host. TPU-native training shards parameters over a
`jax.sharding.Mesh` (tensor/expert parallelism in `parallel/wrapper.py`),
where gathering to one host is exactly the bottleneck checkpoints must
avoid at scale. This module saves each device's shards directly via orbax
(the JAX ecosystem's async multi-host checkpoint library, the moral
equivalent of the reference relying on ND4J serde):

    pw = ParallelWrapper(net, mesh=mesh, param_specs=...)
    pw.fit(...)
    pw.save_checkpoint("/ckpt/step1000")
    ...
    pw2 = ParallelWrapper(net2, mesh=other_mesh, param_specs=...)
    pw2.load_checkpoint("/ckpt/step1000")   # reshards onto other_mesh

Restore reshards automatically: the target shardings come from the
RESTORING wrapper, so a checkpoint written on one mesh layout loads onto
another (or onto more/fewer chips) without an intermediate full-model
host copy. Updater state (Adam moments etc.) and the iteration clock
round-trip, so training resumes exactly (the reference's key checkpoint
property, SURVEY §5)."""
from __future__ import annotations

import functools
import os
from typing import Any, Dict

import jax
import numpy as np


@functools.lru_cache(maxsize=1)
def _checkpointer():
    """One long-lived StandardCheckpointer (orbax's documented pattern) —
    constructing one per save would churn its async-thread machinery."""
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _state_tree(net) -> Dict[str, Any]:
    return {
        "params": net._params,
        "upd_state": net._upd_state,
        "layer_state": net._layer_state,
        "iteration": np.asarray(net.iteration, np.int64),
        "epoch": np.asarray(net.epoch, np.int64),
    }


def save_sharded_checkpoint(path, net) -> None:
    """Write the network's training state shard-by-shard (async under the
    hood; this call blocks until the checkpoint is durable)."""
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(os.fspath(path)), _state_tree(net))
    ckptr.wait_until_finished()


def restore_sharded_checkpoint(path, net, shardings=None) -> None:
    """Restore in place. `shardings`: optional pytree of NamedShardings
    matching (params, upd_state, layer_state) — pass the restoring
    wrapper's shardings to land shards directly on its mesh; omitted, the
    current placement of `net`'s arrays is reused."""
    def _abstract(a, sh=None):
        return jax.ShapeDtypeStruct(
            a.shape, a.dtype,
            sharding=sh if sh is not None else getattr(a, "sharding", None))

    if shardings is None:
        abstract = jax.tree.map(_abstract, _state_tree(net))
    else:
        p_sh, u_sh, l_sh = shardings
        abstract = {
            "params": jax.tree.map(_abstract, net._params, p_sh),
            "upd_state": jax.tree.map(_abstract, net._upd_state, u_sh),
            "layer_state": jax.tree.map(_abstract, net._layer_state, l_sh),
            "iteration": jax.ShapeDtypeStruct((), np.int64),
            "epoch": jax.ShapeDtypeStruct((), np.int64),
        }
    ckptr = _checkpointer()
    restored = ckptr.restore(os.path.abspath(os.fspath(path)), abstract)
    net._params = restored["params"]
    net._upd_state = restored["upd_state"]
    net._layer_state = restored["layer_state"]
    net.iteration = int(restored["iteration"])
    net.epoch = int(restored["epoch"])
    # the device iteration counter is carried through the jitted step;
    # re-seed it from the restored clock
    net._it_device = None
