"""Sharded (multi-chip) checkpointing for distributed training state.

The reference's checkpoint format (`util/ModelSerializer.java:82` — one
zip with the FULL flat parameter vector) assumes the model fits on, and is
gathered to, a single host. TPU-native training shards parameters over a
`jax.sharding.Mesh` (tensor/expert parallelism in `parallel/wrapper.py`),
where gathering to one host is exactly the bottleneck checkpoints must
avoid at scale. This module saves each device's shards directly via orbax
(the JAX ecosystem's async multi-host checkpoint library, the moral
equivalent of the reference relying on ND4J serde):

    pw = ParallelWrapper(net, mesh=mesh, param_specs=...)
    pw.fit(...)
    pw.save_checkpoint("/ckpt/step1000")
    ...
    pw2 = ParallelWrapper(net2, mesh=other_mesh, param_specs=...)
    pw2.load_checkpoint("/ckpt/step1000")   # reshards onto other_mesh

Restore reshards automatically: the target shardings come from the
RESTORING wrapper, so a checkpoint written on one mesh layout loads onto
another (or onto more/fewer chips) without an intermediate full-model
host copy. Updater state (Adam moments etc.) and the iteration clock
round-trip, so training resumes exactly (the reference's key checkpoint
property, SURVEY §5).

Durability: orbax already publishes the checkpoint directory atomically
(write to a temp dir, rename on finalize); on top of that, `save` writes
an integrity manifest sidecar (`<path>.manifest.json` — per-file
size/SHA-256/CRC32 over the finalized tree, step, wall-clock, library
version) and `restore` re-hashes against it, raising
`CheckpointCorruptError` on any drift. Manifest-less directories (older
builds, foreign orbax checkpoints) restore un-verified, with a warning."""
from __future__ import annotations

import functools
import logging
import os
from typing import Any, Dict

import jax
import numpy as np

from deeplearning4j_tpu.util.checkpoint_store import (
    manifest_path_for,
    verify_manifest,
    write_manifest_for,
)

logger = logging.getLogger("deeplearning4j_tpu")


@functools.lru_cache(maxsize=1)
def _checkpointer():
    """One long-lived StandardCheckpointer (orbax's documented pattern) —
    constructing one per save would churn its async-thread machinery."""
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _state_tree(net) -> Dict[str, Any]:
    return {
        "params": net._params,
        "upd_state": net._upd_state,
        "layer_state": net._layer_state,
        "iteration": np.asarray(net.iteration, np.int64),
        "epoch": np.asarray(net.epoch, np.int64),
    }


def save_sharded_checkpoint(path, net) -> None:
    """Write the network's training state shard-by-shard (async under the
    hood; this call blocks until the checkpoint is durable), then publish
    the integrity-manifest sidecar over the finalized tree."""
    import contextlib

    abspath = os.path.abspath(os.fspath(path))
    # retire any OLD sidecar first: overwriting an existing checkpoint
    # must never leave a stale manifest vouching for replaced bytes
    with contextlib.suppress(OSError):
        manifest_path_for(abspath).unlink()
    ckptr = _checkpointer()
    ckptr.save(abspath, _state_tree(net))
    ckptr.wait_until_finished()
    # the manifest publishes only AFTER orbax finalizes the directory
    # rename — a crash before this line leaves an unverifiable (and
    # therefore untrusted) checkpoint, never a manifest vouching for a
    # partial one
    write_manifest_for(abspath, step=int(net.iteration))


def restore_sharded_checkpoint(path, net, shardings=None,
                               verify: bool = True) -> None:
    """Restore in place. `shardings`: optional pytree of NamedShardings
    matching (params, upd_state, layer_state) — pass the restoring
    wrapper's shardings to land shards directly on its mesh; omitted, the
    current placement of `net`'s arrays is reused. With `verify=True`
    (default) the tree is re-hashed against its manifest sidecar first,
    raising `CheckpointCorruptError` on damage; manifest-less
    checkpoints restore un-verified with a warning."""
    abspath = os.path.abspath(os.fspath(path))
    if verify:
        if manifest_path_for(abspath).exists():
            verify_manifest(abspath)
        else:
            logger.warning(
                "sharded checkpoint %s has no integrity manifest "
                "(pre-durability build or foreign orbax checkpoint); "
                "restoring UNVERIFIED", abspath)
    def _abstract(a, sh=None):
        return jax.ShapeDtypeStruct(
            a.shape, a.dtype,
            sharding=sh if sh is not None else getattr(a, "sharding", None))

    if shardings is None:
        abstract = jax.tree.map(_abstract, _state_tree(net))
    else:
        p_sh, u_sh, l_sh = shardings
        abstract = {
            "params": jax.tree.map(_abstract, net._params, p_sh),
            "upd_state": jax.tree.map(_abstract, net._upd_state, u_sh),
            "layer_state": jax.tree.map(_abstract, net._layer_state, l_sh),
            "iteration": jax.ShapeDtypeStruct((), np.int64),
            "epoch": jax.ShapeDtypeStruct((), np.int64),
        }
    ckptr = _checkpointer()
    restored = ckptr.restore(abspath, abstract)
    net._params = restored["params"]
    net._upd_state = restored["upd_state"]
    net._layer_state = restored["layer_state"]
    net.iteration = int(restored["iteration"])
    net.epoch = int(restored["epoch"])
    # the device iteration counter is carried through the jitted step;
    # re-seed it from the restored clock
    net._it_device = None
