"""Time-series + math utilities.

Reference: `deeplearning4j-nn/.../util/TimeSeriesUtils.java` (mask
manipulation, time reversal, last-step extraction) and `util/MathUtils.java`
(the handful of helpers the framework actually uses — most of MathUtils is
superseded by numpy).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


# ------------------------------------------------------------- TimeSeriesUtils
def reverse_time_series(x: np.ndarray,
                        mask: Optional[np.ndarray] = None) -> np.ndarray:
    """Reverse along time, respecting per-example valid lengths: with a
    mask, each example's VALID prefix/suffix is reversed in place rather
    than rotating padding into the front (reference
    `TimeSeriesUtils.reverseTimeSeries`). x: (B, T, F), mask: (B, T)."""
    x = np.asarray(x)
    if mask is None:
        return x[:, ::-1]
    out = np.array(x)
    m = np.asarray(mask) > 0
    for b in range(x.shape[0]):
        idx = np.where(m[b])[0]
        out[b, idx] = x[b, idx[::-1]]
    return out


def extract_last_time_steps(x: np.ndarray,
                            mask: Optional[np.ndarray] = None) -> np.ndarray:
    """(B, T, F) → (B, F) at each example's last VALID step (reference
    `TimeSeriesUtils.pullLastTimeSteps`)."""
    x = np.asarray(x)
    if mask is None:
        return x[:, -1]
    m = np.asarray(mask) > 0
    last = m.shape[1] - 1 - np.argmax(m[:, ::-1], axis=1)
    out = x[np.arange(x.shape[0]), last]
    # an all-masked example has no last valid step: return zeros, matching
    # the fully-masked -> 0 convention used by attention/masked losses
    out = np.where(m.any(axis=1)[:, None], out, 0.0)
    return out


def time_series_mask_to_per_output_mask(mask: np.ndarray,
                                        n_out: int) -> np.ndarray:
    """(B, T) → (B, T, n_out) broadcast mask (reference
    `TimeSeriesUtils.reshapeTimeSeriesMaskToVector` family)."""
    return np.repeat(np.asarray(mask)[:, :, None], n_out, axis=2)


def moving_average(x: np.ndarray, window: int) -> np.ndarray:
    """Trailing moving average over the last axis (reference
    `MathUtils` usage in score smoothing)."""
    x = np.asarray(x, np.float64)
    if window <= 1:
        return x
    c = np.cumsum(np.insert(x, 0, 0.0))
    out = np.empty_like(x)
    for i in range(len(x)):
        lo = max(0, i - window + 1)
        out[i] = (c[i + 1] - c[lo]) / (i + 1 - lo)
    return out


# ------------------------------------------------------------------ MathUtils
def clamp(v: float, lo: float, hi: float) -> float:
    return max(lo, min(hi, v))


def next_power_of_2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def uniform(rng: np.random.Generator, lo: float, hi: float) -> float:
    return float(rng.uniform(lo, hi))


def ss_error(predicted: np.ndarray, actual: np.ndarray) -> float:
    """Sum of squared errors (reference `MathUtils.ssError`)."""
    d = np.asarray(predicted, np.float64) - np.asarray(actual, np.float64)
    return float(np.sum(d * d))


def correlation(a: np.ndarray, b: np.ndarray) -> float:
    """Pearson correlation (reference `MathUtils.correlation`)."""
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    if a.std() == 0 or b.std() == 0:
        return 0.0
    return float(np.corrcoef(a, b)[0, 1])
