"""Serialize a trained network's inference path as portable StableHLO.

The reference's deployment story is JVM serialization (`ModelSerializer`) —
the artifact only runs where DL4J runs. The TPU-native analogue exports the
COMPILED program: `jax.export` lowers the network's forward pass (params
baked in as constants, device-side normalizer and mixed-precision casts
included — exactly what `net.output()` computes) to versioned, serialized
StableHLO that any XLA runtime can load and run with no Python, no
framework, and no pickle on the serving side. Complements
`util/serialization.py` (the training checkpoint): the zip restores a
trainable net; this exports a frozen serving function.

Round-trip and numeric parity vs `net.output()` are tested in
`tests/test_stablehlo_export.py`.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np


def export_inference(net, example_features, path: Optional[str] = None,
                     platforms: Optional[Sequence[str]] = None) -> bytes:
    """Lower `net.output(features)` (eval mode) to serialized StableHLO.

    `net`: an initialized MultiLayerNetwork or ComputationGraph.
    `example_features`: one features array (MLN) or a sequence of arrays
    (CG, one per network input) fixing the serving shapes/dtypes — the
    wire format, e.g. uint8 pixels when a device-side normalizer is
    attached, int32 ids for embedding nets.
    `path`: optionally also write the blob to this file.
    `platforms`: target platforms for the artifact (e.g. `("tpu", "cpu")`
    to serve the same blob on both); default = the exporting platform.

    Returns the serialized bytes. Parameters and layer state are baked
    into the artifact as constants; the exported function takes ONLY the
    feature array(s)."""
    from jax import export as jexport

    net._ensure_init()
    from deeplearning4j_tpu.nn.precision import wire_asarray

    if hasattr(net, "layers"):  # MultiLayerNetwork
        x = wire_asarray(example_features, net.dtype,
                         net._features_are_ids())

        def serve(xx):
            xx = net._prep_features(xx)
            return net._forward_pure(net._params, net._layer_state, xx,
                                     train=False, rng=None, fmask=None)[0]

        args = (jax.ShapeDtypeStruct(x.shape, x.dtype),)
    else:  # ComputationGraph
        feats = (list(example_features)
                 if isinstance(example_features, (list, tuple))
                 else [example_features])
        if len(feats) != len(net.conf.network_inputs):
            raise ValueError(
                f"graph has {len(net.conf.network_inputs)} inputs "
                f"({net.conf.network_inputs}); got {len(feats)} example "
                "feature arrays")
        xs = tuple(wire_asarray(x, net.dtype, ids)
                   for x, ids in zip(feats, net._inputs_are_ids()))

        def serve(*xxs):
            prepped = net._prep_inputs(tuple(xxs))
            acts, _ = net._forward_pure(net._params, net._layer_state,
                                        prepped, train=False, rng=None)
            return tuple(acts[o] for o in net.conf.network_outputs)

        args = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in xs)

    exp = jexport.export(jax.jit(serve),
                         platforms=(None if platforms is None
                                    else list(platforms)))(*args)
    blob = exp.serialize()
    if path is not None:
        with open(path, "wb") as f:
            f.write(bytes(blob))
    return bytes(blob)


def load_inference(src):
    """Load a serialized StableHLO artifact (bytes, or a str/PathLike
    file path) and
    return a callable running it on the default backend — no network
    object, config, or checkpoint needed."""
    import os

    from jax import export as jexport

    if isinstance(src, (str, os.PathLike)):
        with open(src, "rb") as f:
            src = f.read()
    exp = jexport.deserialize(bytearray(src))

    def run(*features):
        out = exp.call(*[np.asarray(f) for f in features])
        if isinstance(out, (list, tuple)):
            return [np.asarray(o) for o in out]
        return np.asarray(out)

    return run
