"""Solvers beyond plain SGD: line search + CG + LBFGS.

Reference: `optimize/Solver.java:41` (dispatch by `OptimizationAlgorithm`,
lines 58-68), `optimize/solvers/BaseOptimizer.java:51`,
`ConjugateGradient.java`, `LBFGS.java`, `LineGradientDescent.java`,
`BackTrackLineSearch.java` (354 LoC).

TPU-native design: the loss/gradient closure over the minibatch is ONE
jitted XLA computation on the flat parameter vector (via
`net.score_function`-style ravel), so each optimizer iteration costs one
device round-trip; the light scalar bookkeeping (Armijo backtracking, CG
beta, LBFGS two-loop over an m-deep history) runs on host between launches
— that control flow is data-dependent and tiny, exactly what should NOT be
traced (SURVEY §7 'compiler-friendly control flow').
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
    OptimizationAlgorithm,
)

log = logging.getLogger(__name__)


def backtrack_line_search(
    f: Callable[[jnp.ndarray], jnp.ndarray],
    x: jnp.ndarray,
    direction: jnp.ndarray,
    value0: float,
    grad0: jnp.ndarray,
    max_iterations: int = 5,
    initial_step: float = 1.0,
    c1: float = 1e-4,
    rho: float = 0.5,
) -> Tuple[float, float]:
    """Armijo backtracking (reference `BackTrackLineSearch.java`): shrink
    step until f(x + αd) ≤ f(x) + c1·α·gᵀd. Returns (step, new_value);
    step=0.0 if no decrease found."""
    slope = float(grad0 @ direction)
    if not np.isfinite(slope) or not np.isfinite(value0):
        # a NaN/Inf gradient or score poisons every Armijo comparison
        # (NaN compares false, so the loop would silently return the
        # blown-up value0) — refuse the step instead
        log.warning("line search: non-finite slope/value (slope=%s, "
                    "value0=%s); rejecting step", slope, value0)
        return 0.0, value0
    if slope >= 0:
        log.debug("line search: non-descent direction (slope=%g)", slope)
        return 0.0, value0
    alpha = initial_step
    for _ in range(max_iterations):
        v = float(f(x + alpha * direction))
        if np.isfinite(v) and v <= value0 + c1 * alpha * slope:
            return alpha, v
        alpha *= rho
    return 0.0, value0


class Solver:
    """Per-minibatch optimizer dispatch (reference `optimize/Solver.java`).

    For SGD the network's own fused train step is the fast path; this class
    covers the line-search family on a fixed batch.
    """

    def __init__(self, net):
        self.net = net
        self.algo = net.conf.global_conf.optimization_algo
        self.max_ls = net.conf.global_conf.max_num_line_search_iterations
        self.last_commit_rejected = False
        # ONE jitted (flat, lstate, batch…) → (value, grad) computation per
        # network, cached on the net — batches are traced ARGUMENTS, so
        # training over many minibatches reuses the same executable instead
        # of recompiling a fresh closure per batch
        if getattr(net, "_solver_jit", None) is None:
            from jax.flatten_util import ravel_pytree

            _, unravel = ravel_pytree(net._params)

            def loss_flat(flat, lstate, feats, labels, fmask, lmask):
                loss, _ = net._loss_pure(unravel(flat), lstate, feats, labels,
                                         fmask, lmask, None, True)
                return loss

            net._solver_jit = (jax.jit(jax.value_and_grad(loss_flat)),
                               jax.jit(loss_flat))
        self._vg_jit, self._val_jit = net._solver_jit

    def optimize(self, ds, iterations: Optional[int] = None) -> float:
        """Run `iterations` optimizer steps on this batch; updates the
        network parameters in place and returns the final score."""
        net = self.net
        iterations = iterations if iterations is not None else \
            net.conf.global_conf.iterations
        feats, labels, fm, lm = net._batch_arrays(ds)
        lstate = net._layer_state
        vg = lambda x: self._vg_jit(x, lstate, feats, labels, fm, lm)
        f = lambda x: self._val_jit(x, lstate, feats, labels, fm, lm)
        x = jnp.asarray(net.params())

        if self.algo == OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            lr = net.conf.global_conf.learning_rate
            v = None
            for _ in range(iterations):
                v, g = vg(x)
                x = x - lr * g
            final = float(v) if v is not None else float(f(x))
            self._commit(x, final)
            return final
        elif self.algo == OptimizationAlgorithm.LINE_GRADIENT_DESCENT:
            final = self._line_gd(vg, f, x, iterations)
            return final  # params set inside
        elif self.algo == OptimizationAlgorithm.CONJUGATE_GRADIENT:
            final = self._cg(vg, f, x, iterations)
            return final
        elif self.algo == OptimizationAlgorithm.LBFGS:
            final = self._lbfgs(vg, f, x, iterations)
            return final
        else:
            raise ValueError(f"unknown optimization algorithm {self.algo}")

    # -- steepest descent + line search ------------------------------------
    def _line_gd(self, vg, f, x, iterations) -> float:
        """Reference `LineGradientDescent.java`: d = −g, Armijo step."""
        v, g = vg(x)
        v = float(v)
        for _ in range(iterations):
            d = -g
            step0 = 1.0 / max(1.0, float(jnp.linalg.norm(g)))
            alpha, v_new = backtrack_line_search(f, x, d, v, g, self.max_ls,
                                                 initial_step=step0)
            if alpha == 0.0:
                break
            x = x + alpha * d
            v, g = vg(x)
            v = float(v)
        self._commit(x, v)
        return v

    # -- nonlinear conjugate gradient --------------------------------------
    def _cg(self, vg, f, x, iterations) -> float:
        """Polak-Ribière+ CG with automatic restart (reference
        `ConjugateGradient.java`)."""
        v, g = vg(x)
        v = float(v)
        d = -g
        for _ in range(iterations):
            step0 = 1.0 / max(1.0, float(jnp.linalg.norm(g)))
            alpha, _ = backtrack_line_search(f, x, d, v, g, self.max_ls,
                                             initial_step=step0)
            if alpha == 0.0:
                # restart along steepest descent; if that fails too, stop
                d = -g
                alpha, _ = backtrack_line_search(f, x, d, v, g, self.max_ls,
                                                 initial_step=step0)
                if alpha == 0.0:
                    break
            x_new = x + alpha * d
            v_new, g_new = vg(x_new)
            v_new = float(v_new)
            # PR+ beta, restart on non-positivity
            denom = float(g @ g)
            beta = max(0.0, float(g_new @ (g_new - g)) / max(denom, 1e-30))
            d = -g_new + beta * d
            x, v, g = x_new, v_new, g_new
        self._commit(x, v)
        return v

    # -- LBFGS --------------------------------------------------------------
    def _lbfgs(self, vg, f, x, iterations, m: int = 10) -> float:
        """Two-loop-recursion LBFGS (reference `LBFGS.java`, history m=10)."""
        v, g = vg(x)
        v = float(v)
        s_hist: List[jnp.ndarray] = []
        y_hist: List[jnp.ndarray] = []
        for _ in range(iterations):
            d = -self._lbfgs_direction(g, s_hist, y_hist)
            alpha, _ = backtrack_line_search(f, x, d, v, g, self.max_ls,
                                             initial_step=1.0)
            if alpha == 0.0:
                # fall back to steepest descent once, else stop
                d = -g
                step0 = 1.0 / max(1.0, float(jnp.linalg.norm(g)))
                alpha, _ = backtrack_line_search(f, x, d, v, g, self.max_ls,
                                                 initial_step=step0)
                if alpha == 0.0:
                    break
                s_hist.clear()
                y_hist.clear()
            x_new = x + alpha * d
            v_new, g_new = vg(x_new)
            v_new = float(v_new)
            s, y = x_new - x, g_new - g
            if float(s @ y) > 1e-10:  # curvature condition
                s_hist.append(s)
                y_hist.append(y)
                if len(s_hist) > m:
                    s_hist.pop(0)
                    y_hist.pop(0)
            x, v, g = x_new, v_new, g_new
        self._commit(x, v)
        return v

    @staticmethod
    def _lbfgs_direction(g, s_hist, y_hist):
        q = g
        alphas = []
        for s, y in zip(reversed(s_hist), reversed(y_hist)):
            rho_i = 1.0 / float(y @ s)
            a = rho_i * float(s @ q)
            alphas.append((a, rho_i))
            q = q - a * y
        if s_hist:
            s, y = s_hist[-1], y_hist[-1]
            gamma = float(s @ y) / max(float(y @ y), 1e-30)
            q = gamma * q
        for (a, rho_i), s, y in zip(reversed(alphas), s_hist, y_hist):
            b = rho_i * float(y @ q)
            q = q + (a - b) * s
        return q

    def _commit(self, x, v) -> bool:
        """Publish candidate parameters + score to the net — UNLESS either
        is non-finite: an LBFGS/CG blow-up must not silently corrupt the
        network (the previous params/score stay; the rejection is
        observable via `last_commit_rejected`, which the attached health
        sentinel reads as a skipped step)."""
        finite_score = v is not None and np.isfinite(v)
        finite_params = bool(jnp.all(jnp.isfinite(x)))
        if not (finite_score and finite_params):
            self.last_commit_rejected = True
            log.warning(
                "solver: rejecting non-finite candidate (score=%s, "
                "params finite=%s); keeping previous parameters", v,
                finite_params)
            return False
        self.net.set_params(np.asarray(x))
        self.net.score_value = v
        return True
