"""Training loop support: listeners, health sentinel, gradient checking."""

from deeplearning4j_tpu.optimize.health import (  # noqa: F401
    BatchQuarantine,
    DivergenceRollback,
    HealthSentinel,
    QuarantineFullError,
    TrainingDivergedError,
    non_finite_batch_reason,
)
from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    CollectScoresIterationListener,
    IterationListener,
    PerformanceListener,
    ScoreIterationListener,
)
