"""Training loop support: listeners + gradient checking."""

from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    CollectScoresIterationListener,
    IterationListener,
    PerformanceListener,
    ScoreIterationListener,
)
