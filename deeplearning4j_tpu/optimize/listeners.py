"""Training listeners (telemetry hooks).

Reference: `optimize/api/IterationListener.java`, `TrainingListener.java`
(onEpochStart/onEpochEnd hooks), impls in `optimize/listeners/`:
`ScoreIterationListener`, `PerformanceListener` (samples/sec, batches/sec),
`CollectScoresIterationListener`.

TPU note: listeners read `model.score_value` which is the host-transferred
scalar loss; anything heavier (param histograms etc. — see ui/stats) should
sample every N iterations to avoid forcing device→host syncs each step.
"""
from __future__ import annotations

import logging
import time
from typing import List, Tuple

logger = logging.getLogger("deeplearning4j_tpu")


class IterationListener:
    """Base hook interface (reference `IterationListener.java`)."""

    def iteration_done(self, model, iteration: int) -> None:
        pass

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass


class ScoreIterationListener(IterationListener):
    """Log score every N iterations (reference
    `ScoreIterationListener.java`)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.print_iterations == 0:
            logger.info("Score at iteration %d is %s", iteration, model.score_value)


class PerformanceListener(IterationListener):
    """Throughput telemetry (reference `PerformanceListener.java`:
    samples/sec and batches/sec every N iterations)."""

    def __init__(self, frequency: int = 10, report_samples: bool = True):
        self.frequency = max(1, frequency)
        self.report_samples = report_samples
        self._last_time = None
        self._last_iter = 0
        self._samples_since = 0
        self.last_samples_per_sec = 0.0
        self.last_batches_per_sec = 0.0

    def record_batch(self, num_samples: int) -> None:
        self._samples_since += num_samples

    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            return
        if iteration - self._last_iter >= self.frequency:
            dt = now - self._last_time
            batches = iteration - self._last_iter
            self.last_batches_per_sec = batches / dt
            self.last_samples_per_sec = self._samples_since / dt if dt > 0 else 0.0
            logger.info("iteration %d: %.1f batches/sec, %.1f samples/sec",
                        iteration, self.last_batches_per_sec, self.last_samples_per_sec)
            self._last_time = now
            self._last_iter = iteration
            self._samples_since = 0


class CollectScoresIterationListener(IterationListener):
    """Collect (iteration, score) pairs (reference
    `CollectScoresIterationListener.java`)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(model.score_value)))
