"""Training listeners (telemetry hooks).

Reference: `optimize/api/IterationListener.java`, `TrainingListener.java`
(onEpochStart/onEpochEnd hooks), impls in `optimize/listeners/`:
`ScoreIterationListener`, `PerformanceListener` (samples/sec, batches/sec),
`CollectScoresIterationListener`.

TPU note: listeners read `model.score_value` which is the host-transferred
scalar loss; anything heavier (param histograms etc. — see ui/stats) should
sample every N iterations to avoid forcing device→host syncs each step.
"""
from __future__ import annotations

import logging
import os
import time
from typing import List, Tuple

logger = logging.getLogger("deeplearning4j_tpu")


class IterationListener:
    """Base hook interface (reference `IterationListener.java`).

    `on_restart`/`on_rollback` have no reference analogue: they fire when
    a fault-tolerant driver (`parallel/fault_tolerance.FaultTolerantTrainer`)
    restores a checkpoint — `on_restart` after a crash/transient failure,
    `on_rollback` after the health sentinel's divergence escalation
    (`optimize/health.HealthSentinel`) — so listeners holding
    iteration-keyed state (score curves, UI streams) can note the
    rollback instead of seeing the iteration clock silently jump
    backwards."""

    def iteration_done(self, model, iteration: int) -> None:
        pass

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass

    def on_restart(self, model, restart_count: int) -> None:
        pass

    def on_rollback(self, model, rollback_count: int) -> None:
        pass


class ScoreIterationListener(IterationListener):
    """Log score every N iterations (reference
    `ScoreIterationListener.java`)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.print_iterations == 0:
            logger.info("Score at iteration %d is %s", iteration, model.score_value)


class PerformanceListener(IterationListener):
    """Throughput telemetry (reference `PerformanceListener.java`:
    samples/sec and batches/sec every N iterations)."""

    def __init__(self, frequency: int = 10, report_samples: bool = True):
        self.frequency = max(1, frequency)
        self.report_samples = report_samples
        self._last_time = None
        self._last_iter = 0
        self._samples_since = 0
        self.last_samples_per_sec = 0.0
        self.last_batches_per_sec = 0.0

    def record_batch(self, num_samples: int) -> None:
        self._samples_since += num_samples

    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            return
        if iteration - self._last_iter >= self.frequency:
            dt = now - self._last_time
            batches = iteration - self._last_iter
            self.last_batches_per_sec = batches / dt
            self.last_samples_per_sec = self._samples_since / dt if dt > 0 else 0.0
            logger.info("iteration %d: %.1f batches/sec, %.1f samples/sec",
                        iteration, self.last_batches_per_sec, self.last_samples_per_sec)
            self._last_time = now
            self._last_iter = iteration
            self._samples_since = 0


class CollectScoresIterationListener(IterationListener):
    """Collect (iteration, score) pairs (reference
    `CollectScoresIterationListener.java`)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(model.score_value)))


class ParamAndGradientIterationListener(IterationListener):
    """Tab-delimited per-parameter and per-gradient summary statistics
    every `frequency` iterations (reference
    `optimize/listeners/ParamAndGradientIterationListener.java`: writes
    mean/absmean/min/max for params and gradients to console or file).

    Gradients are recomputed on the model's last batch when the listener
    fires (the compiled train step donates its gradient buffers, so there
    is nothing to read back) — cost is one extra fwd+bwd per report, only
    while this listener is attached."""

    HEADER = ("iteration\tscore\tname\tp_mean\tp_absmean\tp_min\tp_max"
              "\tg_mean\tg_absmean\tg_min\tg_max")

    def __init__(self, frequency: int = 1, file_path=None,
                 print_console: bool = False):
        self.frequency = max(1, frequency)
        self.file_path = file_path
        self.print_console = print_console
        self.rows: List[str] = []
        if file_path is not None:
            with open(file_path, "w") as f:
                f.write(self.HEADER + "\n")

    def _emit(self, line: str) -> None:
        self.rows.append(line)
        if self.print_console:
            print(line)
        if self.file_path is not None:
            with open(self.file_path, "a") as f:
                f.write(line + "\n")

    def iteration_done(self, model, iteration: int) -> None:
        import numpy as np

        if iteration % self.frequency != 0:
            return
        ds = getattr(model, "_last_batch", None)
        if ds is None:
            return
        grad_flat, score = model.compute_gradient_and_score(ds)
        # walk per-layer named params in flat-vector order
        offset = 0
        for name, arr in self._named(model):
            p = np.asarray(arr).ravel()
            g = grad_flat[offset:offset + p.size]
            offset += p.size
            self._emit("\t".join([
                str(iteration), f"{score:.6g}", name,
                f"{p.mean():.6g}", f"{np.abs(p).mean():.6g}",
                f"{p.min():.6g}", f"{p.max():.6g}",
                f"{g.mean():.6g}", f"{np.abs(g).mean():.6g}",
                f"{g.min():.6g}", f"{g.max():.6g}"]))

    def _named(self, model):
        # iteration order must match ravel_pytree's flat layout: dict keys
        # are flattened in SORTED order
        ps = model._params
        if isinstance(ps, dict):
            for vname in sorted(ps):
                for pname in sorted(ps[vname]):
                    yield f"{vname}_{pname}", ps[vname][pname]
        else:
            for i, d in enumerate(ps):
                for pname in sorted(d):
                    yield f"{i}_{pname}", d[pname]


class CheckpointListener(IterationListener):
    """Periodic checkpointing with keep-last-N rotation — the training-time
    fault-tolerance piece (SURVEY §5 checkpoint/resume: the reference
    checkpoints via `ModelSerializer` and early-stopping savers; this
    listener automates it on an iteration/epoch cadence).

    Saves commit through `util/checkpoint_store.CheckpointStore`: each
    `<dir>/checkpoint_<iteration>.zip` is written to a temp name,
    fsynced, and published with `os.replace` together with an integrity
    sidecar (`...zip.manifest.json` — per-file size/SHA-256/CRC32, step,
    wall-clock, library version), so a crash mid-save can never destroy a
    previously published checkpoint. The `latest` marker file remains as
    a convenience; the restore path trusts manifest verification, not the
    marker. `save_hooks` is the chaos seam
    (`parallel.fault_tolerance.CheckpointCrashInjector`)."""

    def __init__(self, directory, every_n_iterations: int = 0,
                 every_n_epochs: int = 0, keep_last: int = 3,
                 save_hooks=()):
        from deeplearning4j_tpu.util.checkpoint_store import CheckpointStore

        if not every_n_iterations and not every_n_epochs:
            raise ValueError("set every_n_iterations and/or every_n_epochs")
        self.directory = directory
        self.store = CheckpointStore(directory, keep_last=keep_last,
                                     save_hooks=save_hooks)
        self.every_n_iterations = every_n_iterations
        self.every_n_epochs = every_n_epochs
        self.keep_last = self.store.keep_last
        self.saved: List[str] = []
        self._last_saved_iteration = -1

    def _save(self, model, iteration: int) -> None:
        from deeplearning4j_tpu.util.serialization import write_model

        if iteration == self._last_saved_iteration:
            return  # iteration- and epoch-cadence fired at the same step
        # the store owns the atomic commit, so the writer skips its own
        # temp+fsync+replace pass (atomic=False): one fsync per save
        path = self.store.save(
            iteration, lambda tmp: write_model(model, tmp, atomic=False))
        # marked saved only AFTER the publish: a crashed save must not
        # consume this iteration's slot — the rolled-back run re-saves it
        self._last_saved_iteration = iteration
        self.saved.append(str(path))
        self.saved = [p for p in self.saved
                      if os.path.exists(p)][-self.keep_last:]

    def iteration_done(self, model, iteration: int) -> None:
        if self.every_n_iterations and iteration % self.every_n_iterations == 0:
            self._save(model, iteration)

    def on_epoch_end(self, model) -> None:
        if self.every_n_epochs and (model.epoch + 1) % self.every_n_epochs == 0:
            self._save(model, model.iteration)

    @staticmethod
    def last_checkpoint(directory) -> "str | None":
        """Path of the newest VERIFIED checkpoint (manifest re-hash).
        Falls back to the legacy `latest` marker for manifest-less
        directories written by older builds; returns None when nothing
        usable remains (e.g. every retained checkpoint is corrupt — the
        caller should start fresh rather than restore damage)."""
        from deeplearning4j_tpu.util.checkpoint_store import (
            CheckpointCorruptError,
            CheckpointStore,
            manifest_path_for,
        )

        if not os.path.isdir(directory):
            return None  # stay a pure probe: never mkdir as a side effect
        store = CheckpointStore(directory)
        has_manifests = any(
            manifest_path_for(store.path_for(s)).exists()
            for s in store.steps())
        if has_manifests:
            try:
                latest = store.latest_verified()
            except CheckpointCorruptError:
                return None
            if latest is not None:
                return str(latest[1])
        marker = os.path.join(directory, "latest")
        if not os.path.exists(marker):
            return None
        with open(marker) as f:
            name = f.read().strip()
        path = os.path.join(directory, name)
        return path if os.path.exists(path) else None
