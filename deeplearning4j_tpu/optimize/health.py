"""Training health sentinel: non-finite guards, divergence escalation,
poison-batch quarantine.

Reference gap (SURVEY §5): the reference's only defense against numerical
blow-up is the passive `InvalidScoreIterationTerminationCondition`
(`earlystopping/termination/InvalidScoreIterationTerminationCondition.java`)
— it stops training after the damage is done, and nothing in the fit
loops detects a NaN/Inf gradient, a loss spike, or a poisoned minibatch.
On a preemptible TPU fleet that design burns chip-hours on a dead model.
This module closes the third leg of the robustness triangle (PR 1 made
workers survivable, PR 2 made checkpoints durable): surviving the
*training dynamics and the data*.

Pieces:

- `HealthSentinel` — watches every training step. The non-finite check is
  FUSED into the compiled step (`MultiLayerNetwork.set_health_sentinel`):
  the step computes one global gradient-norm scalar (a single reduction
  tree over every gradient leaf — never a per-array pull) and a
  finiteness flag, and commits the candidate parameters/updater/layer
  state ONLY when loss and gradient norm are both finite — a non-finite
  candidate can never overwrite good parameters. The host reads one
  small `(loss, grad_norm, ok)` vector per step (one device→host sync;
  `bench.py sentinel` prices it) and runs EWMA spike detection plus the
  escalation ladder on it.
- The bounded **escalation ladder** — each rung fires after
  `skip_budget` consecutive unhealthy steps (non-finite = skipped
  on-device; a finite spike past `spike_factor ×` the EWMA committed but
  counts as unhealthy):
  1. **skip** — the fused guard already dropped the update; counted and
     logged.
  2. **LR backoff** — every layer's learning rate is multiplied by
     `lr_backoff_factor` (the compiled step bakes LR in, so the jit
     cache is dropped — one recompile per backoff, a rare event), at
     most `backoff_budget` times per incident.
  3. **rollback** — raise `DivergenceRollback`, the control-flow signal
     `parallel.fault_tolerance.FaultTolerantTrainer` consumes to restore
     the last verified-good checkpoint (PR 2's manifest-verified
     `CheckpointStore` walk) and replay; at most `rollback_budget` per
     sentinel. Only armed when a rollback-capable driver set
     `rollback_available` — standalone fits skip this rung.
  4. **give up** — raise the typed `TrainingDivergedError`: never a hang,
     never silent NaN parameters.
- `BatchQuarantine` — a directory of poisoned records with provenance
  sidecars, fed by `streaming.pipeline.StreamingTrainPipeline`
  (`quarantine_dir=`) and `datasets.iterators.QuarantiningDataSetIterator`
  so one bad record costs a quarantine entry, not the pipeline.

`HealthSentinel` state is host-side and NOT thread-safe by design: attach
one sentinel per fit loop (worker clones in the distributed tier do not
inherit it — the master's non-finite result quarantine covers that tier,
`parallel.training_master.NonFiniteWorkerResultError`).
"""
from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Callable, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

_EPS = 1e-8


class TrainingDivergedError(RuntimeError):
    """Training diverged and the sentinel's recovery budget is exhausted
    (skips, LR backoffs, and rollbacks all spent). Typed so drivers can
    distinguish a genuinely dead run from a transient failure — it is
    never swallowed by `FaultTolerantTrainer`'s restart loop."""


class DivergenceRollback(RuntimeError):
    """Control-flow signal, not a failure: the sentinel requests a restore
    of the last verified-good checkpoint + replay. Consumed by
    `FaultTolerantTrainer` (counted as `rollbacks`, fires `on_rollback`
    listeners, never charged against `max_restarts`)."""


class QuarantineFullError(RuntimeError):
    """The quarantine directory hit `max_records` — the stream is
    producing poisoned records faster than anyone is triaging them, which
    is a data-pipeline outage, not noise to absorb silently."""


# ---------------------------------------------------------------------------
# poisoned-batch helpers


def non_finite_array_reason(a, name: str = "array") -> Optional[str]:
    """Why this single array is poisoned, or None when clean: NaN/Inf in a
    floating array (integer arrays are finite by construction). Shared by
    the batch screen below and the serving tier's output screen
    (`serving.model_server` runs it on every inference result before the
    circuit breaker sees the step as a success)."""
    a = np.asarray(a)
    if not np.issubdtype(a.dtype, np.floating):
        return None
    if not np.isfinite(a).all():
        bad = np.count_nonzero(~np.isfinite(a))
        return f"{name} contain {bad} non-finite value(s)"
    return None


def non_finite_batch_reason(ds) -> Optional[str]:
    """Why this batch would poison a training step, or None when clean:
    checks features/labels/masks for NaN/Inf (integer arrays are finite by
    construction and skipped). Host-side screen for stream records —
    cheap next to the fit dispatch it protects."""
    for name in ("features", "labels", "features_mask", "labels_mask"):
        a = getattr(ds, name, None)
        if a is None:
            continue
        reason = non_finite_array_reason(a, name)
        if reason is not None:
            return reason
    return None


class BatchQuarantine:
    """A directory of quarantined records, each an `.npz` payload plus a
    `.json` provenance sidecar (reason, wall-clock, stream position,
    shapes) — the triage trail for poisoned data:

        <dir>/record_<seq>.npz
        <dir>/record_<seq>.json

    Existing records are counted on construction so a restarted pipeline
    appends instead of overwriting. `max_records` bounds the directory;
    exceeding it raises `QuarantineFullError` (a stream that is ALL
    poison is an outage, not noise)."""

    def __init__(self, directory, max_records: int = 256):
        if max_records < 1:
            raise ValueError("max_records must be >= 1")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_records = max_records
        # resume after the HIGHEST existing index, not the count: a
        # triaged (deleted) record must never cause a later one to be
        # overwritten
        existing = [int(p.stem.split("_")[1]) for p in self.record_paths()]
        self._seq = max(existing) + 1 if existing else 0

    def __len__(self) -> int:
        return len(self.record_paths())

    def record_paths(self) -> List[Path]:
        return sorted(self.directory.glob("record_*.npz"))

    def quarantine(self, ds, reason: str, provenance: Optional[dict] = None
                   ) -> Path:
        """Write one poisoned record + provenance; returns the payload
        path. Raises `QuarantineFullError` past `max_records`."""
        if len(self.record_paths()) >= self.max_records:
            raise QuarantineFullError(
                f"quarantine {self.directory} is full "
                f"({self.max_records} records) — the stream is producing "
                "poisoned records faster than they are being triaged")
        seq = self._seq
        self._seq += 1
        payload = self.directory / f"record_{seq}.npz"
        ds.save(payload)
        meta = {
            "seq": seq,
            "reason": reason,
            "wall_clock": time.time(),
            "num_examples": int(ds.num_examples()),
            "features_shape": list(np.shape(ds.features)),
            "features_dtype": str(np.asarray(ds.features).dtype),
        }
        if ds.labels is not None:
            meta["labels_shape"] = list(np.shape(ds.labels))
        if provenance:
            meta["provenance"] = provenance
        sidecar = self.directory / f"record_{seq}.json"
        sidecar.write_text(json.dumps(meta, indent=1, default=str))
        logger.warning("quarantined poisoned record %d -> %s (%s)",
                       seq, payload, reason)
        return payload

    def load(self, seq: int) -> Tuple[object, dict]:
        """(DataSet, provenance dict) for one quarantined record — the
        triage read path."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        ds = DataSet.load(self.directory / f"record_{seq}.npz")
        meta = json.loads(
            (self.directory / f"record_{seq}.json").read_text())
        return ds, meta


# ---------------------------------------------------------------------------
# the sentinel


class HealthSentinel:
    """Per-step training health watchdog + bounded escalation policy.

    Attach with `net.set_health_sentinel(sentinel)`: the compiled train
    step gains the fused finite guard (see module docstring) and calls
    `observe()` once per step with the step's `(loss, grad_norm, ok)`
    device vector — the ONE host sync the sentinel costs. Line-search
    fits (`Solver`) report through `observe_host` with the scalars their
    host loop already materialized.

    Escalation state: `skip_budget` consecutive unhealthy steps trigger
    the next rung (LR backoff → rollback → `TrainingDivergedError`);
    `skip_budget` consecutive HEALTHY steps close the incident (the
    backoff count re-arms; the backed-off LR intentionally stays — the
    replay must not re-diverge at the LR that killed it). EWMA baselines
    update only on healthy steps, so a spike cannot drag its own
    threshold up.
    """

    def __init__(self, spike_factor: float = 10.0, ewma_beta: float = 0.9,
                 warmup_steps: int = 10, skip_budget: int = 3,
                 lr_backoff_factor: float = 0.5, backoff_budget: int = 2,
                 rollback_budget: int = 2,
                 on_event: Optional[Callable[[dict], None]] = None):
        if not (0.0 < ewma_beta < 1.0):
            raise ValueError("ewma_beta must be in (0, 1)")
        if spike_factor <= 1.0:
            raise ValueError("spike_factor must be > 1")
        if not (0.0 < lr_backoff_factor < 1.0):
            raise ValueError("lr_backoff_factor must be in (0, 1)")
        if skip_budget < 1:
            raise ValueError("skip_budget must be >= 1")
        if backoff_budget < 0 or rollback_budget < 0:
            raise ValueError("budgets must be >= 0")
        self.spike_factor = spike_factor
        self.ewma_beta = ewma_beta
        self.warmup_steps = warmup_steps
        self.skip_budget = skip_budget
        self.lr_backoff_factor = lr_backoff_factor
        self.backoff_budget = backoff_budget
        self.rollback_budget = rollback_budget
        self.on_event = on_event
        # armed by a rollback-capable driver (FaultTolerantTrainer);
        # standalone fits skip the rollback rung and fail typed instead
        self.rollback_available = False
        # counters (observable state for tests/telemetry)
        self.steps = 0
        self.skips = 0
        self.spikes = 0
        self.backoffs = 0
        self.rollbacks = 0
        self.lr_scale = 1.0
        self.last_verdict = "ok"
        self.last_step_skipped = False
        # EWMA baselines + streak machine
        self._loss_ewma: Optional[float] = None
        self._gnorm_ewma: Optional[float] = None
        self._healthy_seen = 0
        self._unhealthy_streak = 0
        self._healthy_streak = 0
        self._backoffs_in_incident = 0

    # -- observation entry points ----------------------------------------
    def observe(self, net, health) -> bool:
        """Consume one fused-guard health vector `[loss, grad_norm, ok]`
        (device array — materializing it here is the step's single
        device→host sync). Returns True when the step was healthy; raises
        `DivergenceRollback` / `TrainingDivergedError` per the ladder."""
        h = np.asarray(health, np.float64)
        return self._record(net, float(h[0]), float(h[1]),
                            committed=bool(h[2] >= 0.5))

    def observe_host(self, net, loss, grad_norm: Optional[float] = None,
                     committed: bool = True) -> bool:
        """Host-scalar path (line-search solvers already materialize
        their score; `committed=False` marks a candidate the caller
        rejected, e.g. `Solver._commit`'s non-finite guard)."""
        loss = float("nan") if loss is None else float(loss)
        return self._record(net, loss,
                            None if grad_norm is None else float(grad_norm),
                            committed=committed)

    # -- telemetry --------------------------------------------------------
    def counters(self) -> dict:
        return {"steps": self.steps, "skips": self.skips,
                "spikes": self.spikes, "backoffs": self.backoffs,
                "rollbacks": self.rollbacks, "lr_scale": self.lr_scale}

    def _emit(self, kind: str, **fields) -> None:
        if self.on_event is not None:
            self.on_event({"event": kind, **fields})

    # -- the ladder --------------------------------------------------------
    def _record(self, net, loss: float, gnorm: Optional[float],
                committed: bool) -> bool:
        self.steps += 1
        finite = (committed and np.isfinite(loss)
                  and (gnorm is None or np.isfinite(gnorm)))
        spike = finite and self._is_spike(loss, gnorm)
        self.last_step_skipped = not committed
        if not committed:
            self.skips += 1
        if finite and not spike:
            self._note_healthy(loss, gnorm)
            return True
        it = getattr(net, "iteration", -1)
        if spike:
            self.spikes += 1
            self.last_verdict = "spike"
            logger.warning(
                "HealthSentinel: spike at iteration %d (loss=%g vs EWMA "
                "%s, grad_norm=%s vs EWMA %s, factor %gx)", it, loss,
                self._loss_ewma, gnorm, self._gnorm_ewma,
                self.spike_factor)
        else:
            self.last_verdict = "non-finite"
            logger.warning(
                "HealthSentinel: non-finite step at iteration %d "
                "(loss=%s, grad_norm=%s)%s", it, loss, gnorm,
                "; batch skipped, parameters untouched"
                if not committed else "")
        self._emit(self.last_verdict, iteration=it, loss=loss,
                   grad_norm=gnorm)
        self._unhealthy_streak += 1
        self._healthy_streak = 0
        if self._unhealthy_streak >= self.skip_budget:
            self._unhealthy_streak = 0
            self._escalate(net)
        return False

    def _is_spike(self, loss: float, gnorm: Optional[float]) -> bool:
        if self._healthy_seen < self.warmup_steps:
            return False
        if self._loss_ewma is not None \
                and loss > self.spike_factor * (abs(self._loss_ewma) + _EPS):
            return True
        return (gnorm is not None and self._gnorm_ewma is not None
                and gnorm > self.spike_factor * (self._gnorm_ewma + _EPS))

    def _note_healthy(self, loss: float, gnorm: Optional[float]) -> None:
        self.last_verdict = "ok"
        b = self.ewma_beta
        self._loss_ewma = loss if self._loss_ewma is None \
            else b * self._loss_ewma + (1 - b) * loss
        if gnorm is not None:
            self._gnorm_ewma = gnorm if self._gnorm_ewma is None \
                else b * self._gnorm_ewma + (1 - b) * gnorm
        self._healthy_seen += 1
        self._unhealthy_streak = 0
        self._healthy_streak += 1
        if self._healthy_streak >= self.skip_budget:
            # incident closed: re-arm the backoff budget (the backed-off
            # LR stays — recovery at the lower LR is the stable state)
            self._backoffs_in_incident = 0

    def _escalate(self, net) -> None:
        if self._backoffs_in_incident < self.backoff_budget:
            self._backoff_lr(net)
            return
        if self.rollback_available and self.rollbacks < self.rollback_budget:
            self.rollbacks += 1
            logger.warning(
                "HealthSentinel: requesting rollback %d/%d to the last "
                "verified-good checkpoint (LR backoffs exhausted at "
                "lr_scale=%g)", self.rollbacks, self.rollback_budget,
                self.lr_scale)
            self._emit("rollback", rollbacks=self.rollbacks)
            raise DivergenceRollback(
                f"sustained divergence after {self.backoffs} LR "
                f"backoff(s); rollback {self.rollbacks}/"
                f"{self.rollback_budget} requested")
        raise TrainingDivergedError(
            f"training diverged and the recovery budget is exhausted: "
            f"{self.skips} skipped batch(es), {self.backoffs} LR "
            f"backoff(s) (lr_scale={self.lr_scale:g}), "
            f"{self.rollbacks}/{self.rollback_budget} rollback(s)"
            + ("" if self.rollback_available
               else " (no rollback-capable driver attached)"))

    def _backoff_lr(self, net) -> None:
        self.backoffs += 1
        self._backoffs_in_incident += 1
        self.lr_scale *= self.lr_backoff_factor
        for layer in getattr(net, "layers", []):
            cfg = getattr(layer, "updater_cfg", None)
            if cfg is None:
                continue
            cfg.learning_rate *= self.lr_backoff_factor
            if cfg.bias_learning_rate is not None:
                cfg.bias_learning_rate *= self.lr_backoff_factor
        gc = getattr(getattr(net, "conf", None), "global_conf", None)
        if gc is not None:
            gc.learning_rate *= self.lr_backoff_factor
        # LR is baked into the compiled step: drop the jit caches so the
        # next dispatch recompiles at the reduced rate (one compile per
        # backoff — a rare event by construction)
        net._jit_train = None
        net._jit_scan = None
        logger.warning(
            "HealthSentinel: backing off learning rate x%g (backoff %d, "
            "cumulative lr_scale=%g)", self.lr_backoff_factor,
            self.backoffs, self.lr_scale)
        self._emit("backoff", backoffs=self.backoffs,
                   lr_scale=self.lr_scale)

    def on_rolled_back(self, net=None) -> None:
        """Called by the rollback driver AFTER the checkpoint restore:
        the replay starts from different dynamics, so the streaks and
        EWMA baselines reset and the backoff budget re-arms — but the
        rollback count and the backed-off LR persist (the budget is per
        sentinel, and replaying at the divergent LR would loop)."""
        self._loss_ewma = None
        self._gnorm_ewma = None
        self._healthy_seen = 0
        self._unhealthy_streak = 0
        self._healthy_streak = 0
        self._backoffs_in_incident = 0
        self.last_step_skipped = False
        self.last_verdict = "ok"
