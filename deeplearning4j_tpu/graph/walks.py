"""Random-walk generators (reference
`deeplearning4j-graph/.../iterator/RandomWalkIterator.java`,
`WeightedRandomWalkIterator.java`): fixed-length vertex-sequence streams
feeding DeepWalk's skip-gram."""
from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph


class NoEdges:
    """Walk-termination modes (reference `iterator/NoEdgeHandling.java`)."""

    SELF_LOOP = "self_loop"
    EXCEPTION = "exception"


class RandomWalkIterator:
    """Uniform random walks of fixed length, one starting at every vertex
    (reference `RandomWalkIterator.java`)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 123,
                 no_edge_handling: str = NoEdges.SELF_LOOP):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling

    def _next_vertex(self, rng: np.random.Generator, cur: int) -> int:
        nbrs = self.graph.get_connected_vertices(cur)
        if not nbrs:
            if self.no_edge_handling == NoEdges.EXCEPTION:
                raise ValueError(f"vertex {cur} has no outgoing edges")
            return cur
        return nbrs[int(rng.integers(0, len(nbrs)))]

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        order = rng.permutation(self.graph.num_vertices())
        for start in order:
            walk = [int(start)]
            while len(walk) < self.walk_length:
                walk.append(self._next_vertex(rng, walk[-1]))
            yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional transition probabilities (reference
    `WeightedRandomWalkIterator.java`)."""

    def _next_vertex(self, rng: np.random.Generator, cur: int) -> int:
        edges = self.graph.get_edges_out(cur)
        if not edges:
            if self.no_edge_handling == NoEdges.EXCEPTION:
                raise ValueError(f"vertex {cur} has no outgoing edges")
            return cur
        w = np.array([e.weight for e in edges], np.float64)
        p = w / w.sum()
        return edges[int(rng.choice(len(edges), p=p))].dst
