"""Graph-vector persistence (reference
`deeplearning4j-graph/.../models/GraphVectorSerializer.java`): plain-text
`idx v0 v1 ...` lines."""
from __future__ import annotations

from pathlib import Path
from typing import Tuple, Union

import numpy as np


class GraphVectorSerializer:
    @staticmethod
    def write_graph_vectors(deepwalk, path: Union[str, Path]) -> None:
        table = deepwalk.lookup_table
        with open(path, "w", encoding="utf-8") as f:
            for i in range(table.vocab.num_words()):
                vtx = table.vocab.word_at_index(i)
                vec = " ".join(f"{x:.6f}" for x in np.asarray(table.syn0[i]))
                f.write(f"{vtx} {vec}\n")

    @staticmethod
    def read_graph_vectors(path: Union[str, Path]) -> Tuple[np.ndarray, list]:
        """Returns (vectors ordered by vertex idx, vertex ids)."""
        ids, vecs = [], []
        for line in Path(path).read_text(encoding="utf-8").splitlines():
            parts = line.split(" ")
            ids.append(int(parts[0]))
            vecs.append([float(x) for x in parts[1:]])
        order = np.argsort(ids)
        return np.asarray(vecs, np.float32)[order], [ids[i] for i in order]
