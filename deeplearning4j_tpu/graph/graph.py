"""In-memory graph (reference `deeplearning4j-graph/.../graph/api/IGraph.java`
+ `graph/graph/Graph.java`): vertices with optional values, directed or
undirected weighted edges, adjacency lists."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass
class Vertex:
    idx: int
    value: Any = None


@dataclass
class Edge:
    src: int
    dst: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    """Adjacency-list graph (reference `graph/graph/Graph.java`)."""

    def __init__(self, n_vertices: int, directed: bool = False,
                 values: Optional[Sequence[Any]] = None):
        self.directed = directed
        self._vertices = [Vertex(i, values[i] if values else None)
                          for i in range(n_vertices)]
        self._adj: List[List[Edge]] = [[] for _ in range(n_vertices)]

    # -- construction -------------------------------------------------------
    def add_edge(self, src: int, dst: int, weight: float = 1.0,
                 directed: Optional[bool] = None) -> None:
        directed = self.directed if directed is None else directed
        e = Edge(src, dst, weight, directed)
        self._adj[src].append(e)
        if not directed:
            self._adj[dst].append(Edge(dst, src, weight, directed))

    @staticmethod
    def from_edge_list(edges: Iterable[Tuple[int, int]],
                       n_vertices: Optional[int] = None,
                       directed: bool = False) -> "Graph":
        edges = list(edges)
        if n_vertices is None:
            n_vertices = 1 + max(max(s, d) for s, d in edges)
        g = Graph(n_vertices, directed)
        for s, d in edges:
            g.add_edge(s, d)
        return g

    # -- queries ------------------------------------------------------------
    def num_vertices(self) -> int:
        return len(self._vertices)

    def get_vertex(self, i: int) -> Vertex:
        return self._vertices[i]

    def get_edges_out(self, i: int) -> List[Edge]:
        return list(self._adj[i])

    def get_connected_vertices(self, i: int) -> List[int]:
        return [e.dst for e in self._adj[i]]

    def degree(self, i: int) -> int:
        return len(self._adj[i])
