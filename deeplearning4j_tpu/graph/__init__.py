"""Graph embeddings (reference `deeplearning4j-graph/`, §2.6 of SURVEY.md):
in-memory graph, random walks, DeepWalk skip-gram over walks."""
from deeplearning4j_tpu.graph.graph import Graph, Vertex, Edge  # noqa: F401
from deeplearning4j_tpu.graph.walks import (  # noqa: F401
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk  # noqa: F401
from deeplearning4j_tpu.graph.serializer import GraphVectorSerializer  # noqa: F401
