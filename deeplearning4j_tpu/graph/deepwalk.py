"""DeepWalk: skip-gram over random walks (reference
`deeplearning4j-graph/.../models/deepwalk/DeepWalk.java` +
`GraphHuffman.java`). The reference trains hierarchical softmax with its own
Huffman coder over vertex degrees; here the shared SequenceVectors engine
provides both HS and negative sampling through the jitted scatter kernels
(`nlp/kernels.py`)."""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walks import RandomWalkIterator
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors


class DeepWalk:
    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 10,
                 learning_rate: float = 0.025, negative: int = 5,
                 use_hierarchic_softmax: bool = False,
                 batch_size: int = 1024, seed: int = 123):
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.seed = seed
        self._sv = SequenceVectors(
            layer_size=vector_size, window=window_size,
            min_word_frequency=1.0, negative=negative,
            use_hierarchic_softmax=use_hierarchic_softmax,
            learning_rate=learning_rate, batch_size=batch_size,
            epochs=1, seed=seed, elements_learning_algorithm="skipgram")

    def fit(self, graph: Graph) -> None:
        """Generate walks_per_vertex × num_vertices walks and skip-gram
        them (reference `DeepWalk.fit(GraphWalkIterator)`)."""
        walks: List[List[str]] = []
        for r in range(self.walks_per_vertex):
            it = RandomWalkIterator(graph, self.walk_length,
                                    seed=self.seed + r)
            walks.extend([str(v) for v in walk] for walk in it)
        self._sv.fit(walks)

    # -- query --------------------------------------------------------------
    @property
    def lookup_table(self):
        return self._sv.lookup_table

    @property
    def vocab(self):
        return self._sv.vocab

    def vertex_vector(self, vertex: int) -> Optional[np.ndarray]:
        return self._sv.get_word_vector(str(vertex))

    def similarity(self, v1: int, v2: int) -> float:
        return self._sv.similarity(str(v1), str(v2))

    def verts_nearest(self, vertex: int, top_n: int = 10) -> List[Tuple[int, float]]:
        return [(int(w), s) for w, s in self._sv.words_nearest(str(vertex), top_n)]
