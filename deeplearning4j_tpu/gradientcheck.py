"""Numerical-vs-analytic gradient validation — the correctness backbone.

Reference: `deeplearning4j-nn/.../gradientcheck/GradientCheckUtil.java:62`
(MLN variant; `:194` ComputationGraph; `:305` pretrain layer). The reference
forces fp64 (`DataTypeUtil.setDTypeForContext(DOUBLE)`,
`GradientCheckTests.java:46-48`), eps=1e-6, maxRelError=1e-3 — same defaults
here; build the network with `dtype=jnp.float64` (tests enable jax x64).

The analytic gradient is `jax.grad` of the jitted loss; the numerical
gradient is central differences on the flat parameter vector via the
`ravel_pytree` view.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.datasets.dataset import DataSet

logger = logging.getLogger("deeplearning4j_tpu")


def check_gradients(
    net,
    ds: DataSet,
    eps: float = 1e-6,
    max_rel_error: float = 1e-3,
    min_abs_error: float = 1e-8,
    print_results: bool = False,
    subset: Optional[int] = None,
    seed: int = 0,
) -> bool:
    """Central-difference check of every (or a random `subset` of) parameter
    against the analytic gradient. Returns True iff all checked params pass:
    relError = |analytic - numeric| / (|analytic| + |numeric|) < max_rel_error
    (reference `GradientCheckUtil.checkGradients` pass criterion, with the
    min_abs_error escape hatch for near-zero gradients)."""
    net._ensure_init()
    analytic, _score = net.compute_gradient_and_score(ds)
    flat0, _ = ravel_pytree(net._params)
    # works for both MultiLayerNetwork and ComputationGraph (GradientCheckUtil
    # has separate :62/:194 variants in the reference; one contract here)
    score_at = net.score_function(ds)

    n = flat0.shape[0]
    idxs = np.arange(n)
    if subset is not None and subset < n:
        idxs = np.random.default_rng(seed).choice(n, size=subset, replace=False)

    n_fail = 0
    max_err_seen = 0.0
    flat0_np = np.asarray(flat0)
    for i in idxs:
        basis = np.zeros(n, flat0_np.dtype)
        basis[i] = eps
        plus = float(score_at(jnp.asarray(flat0_np + basis)))
        minus = float(score_at(jnp.asarray(flat0_np - basis)))
        numeric = (plus - minus) / (2.0 * eps)
        a = float(analytic[i])
        denom = abs(a) + abs(numeric)
        rel = abs(a - numeric) / denom if denom > 0 else 0.0
        max_err_seen = max(max_err_seen, rel)
        if rel > max_rel_error and abs(a - numeric) > min_abs_error:
            n_fail += 1
            if print_results:
                logger.warning("param %d FAIL: analytic=%g numeric=%g rel=%g",
                               i, a, numeric, rel)
    if print_results:
        logger.info("gradient check: %d/%d failed, max rel error %g",
                    n_fail, len(idxs), max_err_seen)
    return n_fail == 0
