"""Object-store dataset/model IO (reference `aws/s3/` role, SURVEY §2.4)."""
from __future__ import annotations

import io
from pathlib import Path
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.datasets.iterators import (
    natural_key as _natural_key,  # canonical home for the shard sort key
)


def _ds_to_bytes(ds: DataSet) -> bytes:
    buf = io.BytesIO()
    arrays = {"features": ds.features}
    if ds.labels is not None:
        arrays["labels"] = ds.labels
    if ds.features_mask is not None:
        arrays["features_mask"] = ds.features_mask
    if ds.labels_mask is not None:
        arrays["labels_mask"] = ds.labels_mask
    np.savez(buf, **arrays)
    return buf.getvalue()


def _ds_from_bytes(raw: bytes) -> DataSet:
    z = np.load(io.BytesIO(raw), allow_pickle=False)
    return DataSet(z["features"],
                   z["labels"] if "labels" in z else None,
                   z["features_mask"] if "features_mask" in z else None,
                   z["labels_mask"] if "labels_mask" in z else None)


class DataSetStorage:
    """Key → bytes object store with DataSet/model helpers (reference
    `S3Uploader` / `BaseS3DataSetIterator` surface)."""

    # -- raw object contract (backends implement) -------------------------
    def put_bytes(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get_bytes(self, key: str) -> bytes:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    # -- dataset/model helpers -------------------------------------------
    def put_dataset(self, key: str, ds: DataSet) -> None:
        self.put_bytes(key, _ds_to_bytes(ds))

    def get_dataset(self, key: str) -> DataSet:
        return _ds_from_bytes(self.get_bytes(key))

    def put_model(self, key: str, net) -> None:
        import tempfile

        from deeplearning4j_tpu.util.serialization import write_model

        with tempfile.NamedTemporaryFile(suffix=".zip") as f:
            write_model(net, f.name)
            f.seek(0)
            self.put_bytes(key, Path(f.name).read_bytes())

    def get_model(self, key: str):
        import tempfile

        from deeplearning4j_tpu.util.serialization import restore_model

        with tempfile.NamedTemporaryFile(suffix=".zip") as f:
            f.write(self.get_bytes(key))
            f.flush()
            return restore_model(f.name)


class LocalStorage(DataSetStorage):
    """Filesystem backend — always available; also the test double for the
    gated cloud backends (the reference tests S3 paths against local files
    the same way)."""

    def __init__(self, root):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        p = (self.root / key).resolve()
        # Path.is_relative_to, not a string prefix compare: "/data/bucket"
        # must not admit "/data/bucket-evil"
        if not p.is_relative_to(self.root.resolve()):
            raise ValueError(f"key {key!r} escapes the storage root")
        return p

    def put_bytes(self, key: str, data: bytes) -> None:
        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(data)

    def get_bytes(self, key: str) -> bytes:
        return self._path(key).read_bytes()

    def list_keys(self, prefix: str = "") -> List[str]:
        return sorted(str(f.relative_to(self.root))
                      for f in self.root.rglob("*")
                      if f.is_file() and str(f.relative_to(self.root)).startswith(prefix))

    def exists(self, key: str) -> bool:
        return self._path(key).is_file()


class GCSStorage(DataSetStorage):
    """Google Cloud Storage backend. `client=None` imports the real
    google-cloud-storage package (not bundled here — no egress); inject
    any object with the client surface this class consumes
    (`bucket().blob().upload_from_string/download_as_bytes/exists`,
    `bucket().list_blobs`) to run the SAME key-prefixing/serde code
    against a fake — how CI exercises this path
    (`tests/test_cloud_execute.py::FakeGCSClient`)."""

    def __init__(self, bucket: str, prefix: str = "", client=None):
        if client is None:
            try:
                from google.cloud import storage  # type: ignore
            except ImportError as e:
                raise ImportError(
                    "GCSStorage requires the google-cloud-storage package "
                    "(or pass client=); use LocalStorage in this "
                    "environment") from e
            client = storage.Client()
        self._bucket = client.bucket(bucket)
        self._prefix = prefix.rstrip("/")

    def _key(self, key: str) -> str:
        return f"{self._prefix}/{key}" if self._prefix else key

    def put_bytes(self, key: str, data: bytes) -> None:
        self._bucket.blob(self._key(key)).upload_from_string(data)

    def get_bytes(self, key: str) -> bytes:
        return self._bucket.blob(self._key(key)).download_as_bytes()

    def list_keys(self, prefix: str = "") -> List[str]:
        full = self._key(prefix)
        skip = len(self._prefix) + 1 if self._prefix else 0
        return sorted(b.name[skip:] for b in self._bucket.list_blobs(prefix=full))

    def exists(self, key: str) -> bool:
        return self._bucket.blob(self._key(key)).exists()


class StorageDataSetIterator(DataSetIterator):
    """STREAM DataSets from a key prefix, one object in memory at a time
    (reference `BaseS3DataSetIterator.java` — its `next()` opens the next
    S3 object): the training set lives in the bucket and is never
    downloaded up front, so it may be far larger than host storage.

    `async_supported` is True — wrap in `AsyncDataSetIterator` and the
    next object's download overlaps the current batch's device step (the
    same producer/consumer overlap the host infeed pipeline uses).
    `reset()` re-lists the prefix, so shards appended between epochs
    become visible on the next pass."""

    def __init__(self, storage: DataSetStorage, prefix: str = ""):
        self.storage = storage
        self.prefix = prefix
        self._keys: Optional[List[str]] = None
        self._pos = 0

    def reset(self) -> None:
        # natural sort: shard writers number keys, often WITHOUT zero
        # padding ("shard_10" must follow "shard_9", not "shard_1") —
        # iteration order must be the write order regardless of backend
        # listing order
        self._keys = sorted(self.storage.list_keys(self.prefix),
                            key=_natural_key)
        self._pos = 0

    def has_next(self) -> bool:
        if self._keys is None:
            self.reset()
        return self._pos < len(self._keys)

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds = self.storage.get_dataset(self._keys[self._pos])
        self._pos += 1
        return ds

    def batch(self) -> int:
        return -1
