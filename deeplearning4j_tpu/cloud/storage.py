"""Object-store dataset/model IO (reference `aws/s3/` role, SURVEY §2.4)."""
from __future__ import annotations

import io
from pathlib import Path
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.datasets.iterators import (
    natural_key as _natural_key,  # canonical home for the shard sort key
)


def _ds_to_bytes(ds: DataSet) -> bytes:
    buf = io.BytesIO()
    arrays = {"features": ds.features}
    if ds.labels is not None:
        arrays["labels"] = ds.labels
    if ds.features_mask is not None:
        arrays["features_mask"] = ds.features_mask
    if ds.labels_mask is not None:
        arrays["labels_mask"] = ds.labels_mask
    np.savez(buf, **arrays)
    return buf.getvalue()


def _ds_from_bytes(raw: bytes) -> DataSet:
    z = np.load(io.BytesIO(raw), allow_pickle=False)
    return DataSet(z["features"],
                   z["labels"] if "labels" in z else None,
                   z["features_mask"] if "features_mask" in z else None,
                   z["labels_mask"] if "labels_mask" in z else None)


class DataSetStorage:
    """Key → bytes object store with DataSet/model helpers (reference
    `S3Uploader` / `BaseS3DataSetIterator` surface)."""

    # -- raw object contract (backends implement) -------------------------
    def put_bytes(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get_bytes(self, key: str) -> bytes:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    # -- dataset/model helpers -------------------------------------------
    def put_dataset(self, key: str, ds: DataSet) -> None:
        self.put_bytes(key, _ds_to_bytes(ds))

    def get_dataset(self, key: str) -> DataSet:
        return _ds_from_bytes(self.get_bytes(key))

    def put_model(self, key: str, net) -> None:
        import tempfile

        from deeplearning4j_tpu.util.serialization import write_model

        with tempfile.NamedTemporaryFile(suffix=".zip") as f:
            write_model(net, f.name)
            f.seek(0)
            self.put_bytes(key, Path(f.name).read_bytes())

    def get_model(self, key: str):
        import tempfile

        from deeplearning4j_tpu.util.serialization import restore_model

        with tempfile.NamedTemporaryFile(suffix=".zip") as f:
            f.write(self.get_bytes(key))
            f.flush()
            return restore_model(f.name)


class LocalStorage(DataSetStorage):
    """Filesystem backend — always available; also the test double for the
    gated cloud backends (the reference tests S3 paths against local files
    the same way)."""

    def __init__(self, root):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        p = (self.root / key).resolve()
        # Path.is_relative_to, not a string prefix compare: "/data/bucket"
        # must not admit "/data/bucket-evil"
        if not p.is_relative_to(self.root.resolve()):
            raise ValueError(f"key {key!r} escapes the storage root")
        return p

    def put_bytes(self, key: str, data: bytes) -> None:
        from deeplearning4j_tpu.util.checkpoint_store import (
            atomic_write_bytes,
        )

        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        # atomic publish: an interrupted put must not leave a truncated
        # object that a later get would hand to a model restore
        atomic_write_bytes(p, data)

    def get_bytes(self, key: str) -> bytes:
        return self._path(key).read_bytes()

    def list_keys(self, prefix: str = "") -> List[str]:
        return sorted(str(f.relative_to(self.root))
                      for f in self.root.rglob("*")
                      if f.is_file() and str(f.relative_to(self.root)).startswith(prefix))

    def exists(self, key: str) -> bool:
        return self._path(key).is_file()


class RetryingStorage(DataSetStorage):
    """Bounded-backoff retry + post-transfer checksum re-verification for
    ANY `DataSetStorage` backend — the cloud-transfer leg of the durable
    checkpoint subsystem (`util/checkpoint_store.py`), under the same
    retry discipline as PR 1's `RetryingParameterServerClient`.

    - transient transport failures (`ConnectionError`/`OSError`/
      `TimeoutError`) retry after `backoff × backoff_multiplier^attempt`
      seconds, at most `max_retries` retries, then re-raise;
    - with `verify=True` (default), every `put_bytes` is read back and
      its SHA-256 compared against what was sent — an object store that
      corrupted bytes in flight is retried like a transport failure, and
      exhaustion raises `CheckpointCorruptError`. `get_bytes` accepts an
      optional `expected_sha256` for the symmetric download check (used
      by `CheckpointStore.download`, whose manifests carry the digests).

    Counters (`attempts`, `retries`) are observability for chaos tests."""

    def __init__(self, storage: DataSetStorage, max_retries: int = 3,
                 backoff: float = 0.05, backoff_multiplier: float = 2.0,
                 verify: bool = True):
        self._storage = storage
        self.max_retries = max_retries
        self.backoff = backoff
        self.backoff_multiplier = backoff_multiplier
        self.verify = verify
        self.attempts = 0
        self.retries = 0

    def _retry(self, what: str, fn, extra_retryable: tuple = ()):
        from deeplearning4j_tpu.util.checkpoint_store import (
            retry_with_backoff,
        )

        def counted():
            self.attempts += 1
            return fn()

        before = self.attempts

        try:
            return retry_with_backoff(
                counted, what=what, max_retries=self.max_retries,
                backoff=self.backoff,
                backoff_multiplier=self.backoff_multiplier,
                retryable=(ConnectionError, OSError, TimeoutError)
                + extra_retryable)
        finally:
            self.retries += max(0, self.attempts - before - 1)

    def put_bytes(self, key: str, data: bytes) -> None:
        import hashlib

        from deeplearning4j_tpu.util.checkpoint_store import (
            CheckpointCorruptError,
        )

        if not self.verify:
            self._retry(f"put {key}", lambda: self._storage.put_bytes(key, data))
            return
        want = hashlib.sha256(data).hexdigest()

        def _put_verified():
            self._storage.put_bytes(key, data)
            got = hashlib.sha256(self._storage.get_bytes(key)).hexdigest()
            if got != want:
                raise CheckpointCorruptError(
                    f"upload of {key!r} corrupted in transit "
                    "(read-back digest mismatch)")

        self._retry(f"put {key}", _put_verified,
                    extra_retryable=(CheckpointCorruptError,))

    def get_bytes(self, key: str,
                  expected_sha256: "str | None" = None) -> bytes:
        import hashlib

        from deeplearning4j_tpu.util.checkpoint_store import (
            CheckpointCorruptError,
        )

        def _get():
            data = self._storage.get_bytes(key)
            if expected_sha256 is not None \
                    and hashlib.sha256(data).hexdigest() != expected_sha256:
                raise CheckpointCorruptError(
                    f"download of {key!r} corrupted in transit "
                    "(digest mismatch)")
            return data

        return self._retry(f"get {key}", _get,
                           extra_retryable=(CheckpointCorruptError,)
                           if expected_sha256 is not None else ())

    def list_keys(self, prefix: str = "") -> List[str]:
        return self._retry(f"list {prefix!r}",
                           lambda: self._storage.list_keys(prefix))

    def exists(self, key: str) -> bool:
        return self._retry(f"exists {key}",
                           lambda: self._storage.exists(key))


class GCSStorage(DataSetStorage):
    """Google Cloud Storage backend. `client=None` imports the real
    google-cloud-storage package (not bundled here — no egress); inject
    any object with the client surface this class consumes
    (`bucket().blob().upload_from_string/download_as_bytes/exists`,
    `bucket().list_blobs`) to run the SAME key-prefixing/serde code
    against a fake — how CI exercises this path
    (`tests/test_cloud_execute.py::FakeGCSClient`)."""

    def __init__(self, bucket: str, prefix: str = "", client=None):
        if client is None:
            try:
                from google.cloud import storage  # type: ignore
            except ImportError as e:
                raise ImportError(
                    "GCSStorage requires the google-cloud-storage package "
                    "(or pass client=); use LocalStorage in this "
                    "environment") from e
            client = storage.Client()
        self._bucket = client.bucket(bucket)
        self._prefix = prefix.rstrip("/")

    def _key(self, key: str) -> str:
        return f"{self._prefix}/{key}" if self._prefix else key

    def put_bytes(self, key: str, data: bytes) -> None:
        self._bucket.blob(self._key(key)).upload_from_string(data)

    def get_bytes(self, key: str) -> bytes:
        return self._bucket.blob(self._key(key)).download_as_bytes()

    def list_keys(self, prefix: str = "") -> List[str]:
        full = self._key(prefix)
        skip = len(self._prefix) + 1 if self._prefix else 0
        return sorted(b.name[skip:] for b in self._bucket.list_blobs(prefix=full))

    def exists(self, key: str) -> bool:
        return self._bucket.blob(self._key(key)).exists()


class StorageDataSetIterator(DataSetIterator):
    """STREAM DataSets from a key prefix, one object in memory at a time
    (reference `BaseS3DataSetIterator.java` — its `next()` opens the next
    S3 object): the training set lives in the bucket and is never
    downloaded up front, so it may be far larger than host storage.

    `async_supported` is True — wrap in `AsyncDataSetIterator` and the
    next object's download overlaps the current batch's device step (the
    same producer/consumer overlap the host infeed pipeline uses).
    `reset()` re-lists the prefix, so shards appended between epochs
    become visible on the next pass."""

    def __init__(self, storage: DataSetStorage, prefix: str = ""):
        self.storage = storage
        self.prefix = prefix
        self._keys: Optional[List[str]] = None
        self._pos = 0

    def reset(self) -> None:
        # natural sort: shard writers number keys, often WITHOUT zero
        # padding ("shard_10" must follow "shard_9", not "shard_1") —
        # iteration order must be the write order regardless of backend
        # listing order
        self._keys = sorted(self.storage.list_keys(self.prefix),
                            key=_natural_key)
        self._pos = 0

    def has_next(self) -> bool:
        if self._keys is None:
            self.reset()
        return self._pos < len(self._keys)

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds = self.storage.get_dataset(self._keys[self._pos])
        self._pos += 1
        return ds

    def batch(self) -> int:
        return -1
