"""Cloud dataset/model storage + cluster provisioning descriptors.

Reference: `deeplearning4j-aws` (SURVEY §2.4) — S3 dataset IO
(`S3Uploader.java`, `BaseS3DataSetIterator.java`) and EC2 cluster
provisioning (`ClusterSetup.java`). TPU-native equivalents: an object-store
abstraction with a local-filesystem backend (always available) and a gated
GCS backend, plus a TPU-pod provisioning descriptor that renders the
`gcloud` commands (provisioning itself is infrastructure, not framework —
the descriptor keeps it scriptable and testable without egress).
"""
from deeplearning4j_tpu.cloud.storage import (
    DataSetStorage,
    GCSStorage,
    LocalStorage,
    StorageDataSetIterator,
)
from deeplearning4j_tpu.cloud.provision import TpuPodSpec

__all__ = [
    "DataSetStorage",
    "GCSStorage",
    "LocalStorage",
    "StorageDataSetIterator",
    "TpuPodSpec",
]
