"""TPU pod provisioning descriptor (reference `aws/ec2/provision/
ClusterSetup.java` role, SURVEY §2.4).

The reference shells out to the AWS SDK to stand up EC2 workers. The TPU
equivalent is a TPU-VM/pod slice; actually creating one is an infra action
this environment cannot perform (no egress), so the descriptor renders the
exact `gcloud` commands — scriptable, reviewable, testable."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class TpuPodSpec:
    """Describes a TPU pod slice for a training job."""

    name: str
    accelerator_type: str = "v5litepod-8"  # e.g. v5litepod-8, v4-32
    zone: str = "us-central1-a"
    runtime_version: str = "tpu-ubuntu2204-base"
    project: str = ""
    preemptible: bool = False
    labels: Dict[str, str] = field(default_factory=dict)

    def create_command(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "create", self.name,
               f"--zone={self.zone}",
               f"--accelerator-type={self.accelerator_type}",
               f"--version={self.runtime_version}"]
        if self.project:
            cmd.append(f"--project={self.project}")
        if self.preemptible:
            cmd.append("--preemptible")
        if self.labels:
            cmd.append("--labels=" + ",".join(
                f"{k}={v}" for k, v in sorted(self.labels.items())))
        return cmd

    def delete_command(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "delete", self.name,
               f"--zone={self.zone}", "--quiet"]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd

    def ssh_command(self, worker: str = "all", command: str = "") -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", self.name,
               f"--zone={self.zone}", f"--worker={worker}"]
        if command:
            cmd.append(f"--command={command}")
        return cmd

    @property
    def num_chips(self) -> int:
        """Chip count from the accelerator type. The numeric suffix counts
        CHIPS for v5e/v5p/v6e-style names (v5litepod-8 → 8) but TENSORCORES
        for v2/v3/v4 (v4-32 → 16 chips: 2 cores per chip)."""
        gen, _, suffix = self.accelerator_type.rpartition("-")
        try:
            n = int(suffix)
        except ValueError:
            raise ValueError(
                f"cannot parse chip count from accelerator type "
                f"{self.accelerator_type!r}")
        if gen in ("v2", "v3", "v4"):
            return n // 2
        return n
