"""TPU pod provisioning descriptor (reference `aws/ec2/provision/
ClusterSetup.java` role, SURVEY §2.4).

The reference shells out to the AWS SDK to stand up EC2 workers. The TPU
equivalent is a TPU-VM/pod slice; actually creating one is an infra action
this environment cannot perform (no egress), so the descriptor renders the
exact `gcloud` commands — scriptable, reviewable, testable."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class TpuPodSpec:
    """Describes a TPU pod slice for a training job."""

    name: str
    accelerator_type: str = "v5litepod-8"  # e.g. v5litepod-8, v4-32
    zone: str = "us-central1-a"
    runtime_version: str = "tpu-ubuntu2204-base"
    project: str = ""
    preemptible: bool = False
    labels: Dict[str, str] = field(default_factory=dict)

    def create_command(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "create", self.name,
               f"--zone={self.zone}",
               f"--accelerator-type={self.accelerator_type}",
               f"--version={self.runtime_version}"]
        if self.project:
            cmd.append(f"--project={self.project}")
        if self.preemptible:
            cmd.append("--preemptible")
        if self.labels:
            cmd.append("--labels=" + ",".join(
                f"{k}={v}" for k, v in sorted(self.labels.items())))
        return cmd

    def delete_command(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "delete", self.name,
               f"--zone={self.zone}", "--quiet"]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd

    def ssh_command(self, worker: str = "all", command: str = "") -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", self.name,
               f"--zone={self.zone}", f"--worker={worker}"]
        if command:
            cmd.append(f"--command={command}")
        return cmd

    @property
    def num_chips(self) -> int:
        """Chip count from the accelerator type. The numeric suffix counts
        CHIPS for v5e/v5p/v6e-style names (v5litepod-8 → 8) but TENSORCORES
        for v2/v3/v4 (v4-32 → 16 chips: 2 cores per chip)."""
        gen, _, suffix = self.accelerator_type.rpartition("-")
        try:
            n = int(suffix)
        except ValueError:
            raise ValueError(
                f"cannot parse chip count from accelerator type "
                f"{self.accelerator_type!r}")
        if gen in ("v2", "v3", "v4"):
            return n // 2
        return n


class ClusterSetup:
    """Executes the rendered provisioning commands (the reference's
    `ClusterSetup.java` actually stands up the cluster; rendering-only was
    this module's r2 state). `execute=False` stays the review path: the
    command is returned, nothing runs. `execute=True` runs it via
    subprocess and raises with the tool's stderr on failure.

    `gcloud_binary`: override the executable — CI proves the execute path
    against a fake gcloud double without egress
    (`tests/test_cloud_execute.py`), the same seam a bastion/wrapper
    script would use in production."""

    def __init__(self, spec: TpuPodSpec, gcloud_binary: str = "gcloud"):
        self.spec = spec
        self.gcloud_binary = gcloud_binary

    def _run(self, cmd: List[str], execute: bool):
        # substitute the binary in BOTH paths: the rendered command must
        # be exactly what --execute would run (an operator copy-pasting a
        # render that said plain `gcloud` while execute used a wrapper
        # would invoke the wrong tool)
        cmd = [self.gcloud_binary] + cmd[1:]
        if not execute:
            return cmd
        import subprocess

        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(
                f"provisioning command failed ({res.returncode}): "
                f"{' '.join(cmd)}\n{res.stderr.strip()}")
        return res

    def create(self, execute: bool = False):
        return self._run(self.spec.create_command(), execute)

    def delete(self, execute: bool = False):
        return self._run(self.spec.delete_command(), execute)

    def ssh(self, command: str = "", worker: str = "all",
            execute: bool = False):
        return self._run(self.spec.ssh_command(worker, command), execute)


def _main() -> None:
    """CLI: render (default) or --execute the provisioning commands.

        python -m deeplearning4j_tpu.cloud.provision create \
            --name pod0 --accelerator-type v5litepod-8 [--execute]
    """
    import argparse
    import shlex
    import sys

    ap = argparse.ArgumentParser(description=_main.__doc__)
    ap.add_argument("action", choices=["create", "delete", "ssh"])
    ap.add_argument("--name", required=True)
    ap.add_argument("--accelerator-type", default="v5litepod-8")
    ap.add_argument("--zone", default="us-central1-a")
    ap.add_argument("--runtime-version", default="tpu-ubuntu2204-base")
    ap.add_argument("--project", default="")
    ap.add_argument("--preemptible", action="store_true")
    ap.add_argument("--command", default="", help="ssh remote command")
    ap.add_argument("--worker", default="all")
    ap.add_argument("--execute", action="store_true",
                    help="actually run the command (default: render only)")
    ap.add_argument("--gcloud", default="gcloud",
                    help="gcloud executable (test doubles / wrappers)")
    args = ap.parse_args()
    spec = TpuPodSpec(name=args.name, accelerator_type=args.accelerator_type,
                      zone=args.zone, runtime_version=args.runtime_version,
                      project=args.project, preemptible=args.preemptible)
    setup = ClusterSetup(spec, gcloud_binary=args.gcloud)
    fn = {"create": setup.create, "delete": setup.delete,
          "ssh": lambda execute: setup.ssh(args.command, args.worker,
                                           execute)}[args.action]
    out = fn(execute=args.execute)
    if args.execute:
        sys.stdout.write(out.stdout)
        print(f"EXECUTED rc={out.returncode}")
    else:
        print(shlex.join(out))


if __name__ == "__main__":
    _main()
