"""Keras model import: HDF5 / JSON → TPU-native network config + weights.

Reference: `deeplearning4j-modelimport/src/main/java/org/deeplearning4j/nn/
modelimport/keras/` — `KerasModelImport.java` (entry points),
`KerasModel.java:57` (config mapping :153-273), `KerasSequentialModel.java`,
`KerasLayer.java` (per-layer-type translation). The reference reads HDF5 via
JavaCPP hdf5 presets; here we read with h5py on the host — model import is
pure host-side ETL, the resulting network then runs through the jitted XLA
step like any natively-built one.

Supports both Keras 1.x ("th"/"tf" dim orderings, per-gate LSTM weights) and
Keras 2.x (channels_first/channels_last, fused LSTM kernels) HDF5 files, for
Sequential (→ MultiLayerNetwork) and functional Model (→ ComputationGraph)
architectures.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.nn.conf.computation_graph_configuration import (
    ElementWiseOp,
    ElementWiseVertex,
    GraphBuilder,
    MergeVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    ConvolutionMode,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    GravesLSTM,
    Layer,
    OutputLayer,
    PoolingType,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction


class InvalidKerasConfigurationException(ValueError):
    """Malformed Keras config (reference
    `InvalidKerasConfigurationException.java`)."""


class UnsupportedKerasConfigurationException(ValueError):
    """Valid Keras config using features we don't map (reference
    `UnsupportedKerasConfigurationException.java`)."""


# ---------------------------------------------------------------------------
# mappings

_ACTIVATIONS: Dict[str, Activation] = {
    "linear": Activation.IDENTITY,
    "relu": Activation.RELU,
    "softmax": Activation.SOFTMAX,
    "sigmoid": Activation.SIGMOID,
    "hard_sigmoid": Activation.HARDSIGMOID,
    "tanh": Activation.TANH,
    "softplus": Activation.SOFTPLUS,
    "softsign": Activation.SOFTSIGN,
    "elu": Activation.ELU,
    "selu": Activation.SELU,
    "gelu": Activation.GELU,
    "swish": Activation.SWISH,
}

_LOSSES: Dict[str, LossFunction] = {
    "categorical_crossentropy": LossFunction.MCXENT,
    "sparse_categorical_crossentropy": LossFunction.MCXENT,
    "binary_crossentropy": LossFunction.XENT,
    "mean_squared_error": LossFunction.MSE,
    "mse": LossFunction.MSE,
    "mean_absolute_error": LossFunction.MEAN_ABSOLUTE_ERROR,
    "mae": LossFunction.MEAN_ABSOLUTE_ERROR,
    "mean_absolute_percentage_error": LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR,
    "mean_squared_logarithmic_error": LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR,
    "squared_hinge": LossFunction.SQUARED_HINGE,
    "hinge": LossFunction.HINGE,
    "kullback_leibler_divergence": LossFunction.KL_DIVERGENCE,
    "kld": LossFunction.KL_DIVERGENCE,
    "poisson": LossFunction.POISSON,
    "cosine_proximity": LossFunction.COSINE_PROXIMITY,
}


def map_activation(name: Optional[str]) -> Activation:
    if not name:
        return Activation.IDENTITY
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise UnsupportedKerasConfigurationException(
            f"unknown Keras activation {name!r}") from None


def map_loss(name: str) -> LossFunction:
    try:
        return _LOSSES[name]
    except KeyError:
        raise UnsupportedKerasConfigurationException(
            f"unknown Keras loss {name!r}") from None


def _pair(v, default=None) -> Tuple[int, int]:
    if v is None:
        return default
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _conv_mode(cfg: dict) -> ConvolutionMode:
    mode = cfg.get("border_mode") or cfg.get("padding") or "valid"
    if mode == "valid":
        return ConvolutionMode.TRUNCATE
    if mode == "same":
        return ConvolutionMode.SAME
    raise UnsupportedKerasConfigurationException(
        f"unsupported Keras border mode {mode!r}")


def _dim_ordering(cfg: dict) -> str:
    """'th' (channels_first, NCHW) or 'tf' (channels_last, NHWC)."""
    v = cfg.get("dim_ordering") or cfg.get("data_format")
    if v in ("th", "channels_first"):
        return "th"
    return "tf"


def _input_type_from_shape(shape: Sequence[Optional[int]],
                           ordering: str) -> InputType:
    """batch_input_shape (batch dim stripped) → InputType."""
    dims = [d for d in shape]
    if len(dims) == 3:  # image
        if ordering == "th":
            c, h, w = dims
        else:
            h, w, c = dims
        return InputType.convolutional(int(h), int(w), int(c))
    if len(dims) == 2:  # time series (T, F)
        t, f = dims
        return InputType.recurrent(int(f), int(t) if t else -1)
    if len(dims) == 1:
        return InputType.feed_forward(int(dims[0]))
    raise UnsupportedKerasConfigurationException(
        f"cannot infer InputType from input shape {shape!r}")


# ---------------------------------------------------------------------------
# per-layer translation (reference KerasLayer per-type translation)


def _units(cfg: dict, class_name: str) -> int:
    v = cfg.get("output_dim") or cfg.get("units") or cfg.get("nb_filter") \
        or cfg.get("filters")
    if v is None:
        raise InvalidKerasConfigurationException(
            f"{class_name} config has no output_dim/units/filters")
    return int(v)


def map_keras_layer(class_name: str, cfg: dict) -> Optional[Layer]:
    """One Keras layer config → our Layer config, or None for structural
    no-op layers (Flatten/Reshape/InputLayer — shape plumbing the builder's
    InputType inference + auto-preprocessors already performs)."""
    act = map_activation(cfg.get("activation"))
    name = cfg.get("name")
    if class_name == "Dense":
        return DenseLayer(name=name, activation=act,
                          n_out=_units(cfg, class_name))
    if class_name == "Activation":
        return ActivationLayer(name=name, activation=act)
    if class_name in ("Dropout", "SpatialDropout2D"):
        p = cfg.get("p", cfg.get("rate", 0.0))
        return DropoutLayer(name=name, dropout=float(p))
    if class_name in ("Convolution2D", "Conv2D"):
        n_out = _units(cfg, class_name)
        if "nb_row" in cfg:
            kernel = (int(cfg["nb_row"]), int(cfg["nb_col"]))
        else:
            kernel = _pair(cfg.get("kernel_size"))
        stride = _pair(cfg.get("subsample") or cfg.get("strides"), (1, 1))
        return ConvolutionLayer(name=name, activation=act, n_out=int(n_out),
                                kernel=kernel, stride=stride,
                                convolution_mode=_conv_mode(cfg))
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        pool = (PoolingType.MAX if class_name.startswith("Max")
                else PoolingType.AVG)
        kernel = _pair(cfg.get("pool_size"), (2, 2))
        stride = _pair(cfg.get("strides"), kernel)
        return SubsamplingLayer(name=name, pooling_type=pool, kernel=kernel,
                                stride=stride, convolution_mode=_conv_mode(cfg))
    if class_name in ("GlobalMaxPooling1D", "GlobalMaxPooling2D",
                      "GlobalAveragePooling1D", "GlobalAveragePooling2D"):
        pool = (PoolingType.MAX if "Max" in class_name else PoolingType.AVG)
        return GlobalPoolingLayer(name=name, pooling_type=pool)
    if class_name == "BatchNormalization":
        if cfg.get("mode", 0) not in (0, 2):
            raise UnsupportedKerasConfigurationException(
                f"Keras BatchNormalization mode {cfg['mode']} not supported")
        return BatchNormalization(name=name,
                                  eps=float(cfg.get("epsilon", 1e-5)),
                                  decay=float(cfg.get("momentum", 0.99)))
    if class_name == "Embedding":
        return EmbeddingLayer(name=name, activation=act,
                              n_in=int(cfg["input_dim"]),
                              n_out=_units(cfg, class_name))
    if class_name == "LSTM":
        n_out = _units(cfg, class_name)
        # Keras defaults: cell activation tanh, gate (recurrent) sigmoid
        act = map_activation(cfg.get("activation") or "tanh")
        gate = map_activation(cfg.get("inner_activation")
                              or cfg.get("recurrent_activation") or "sigmoid")
        if not cfg.get("return_sequences", False):
            raise UnsupportedKerasConfigurationException(
                "LSTM with return_sequences=False: add a LastTimeStep/global "
                "pooling stage explicitly (reference KerasLayer has the same "
                "restriction for sequence outputs)")
        return GravesLSTM(name=name, activation=act,
                          gate_activation=gate, n_out=n_out,
                          forget_gate_bias_init=1.0 if cfg.get(
                              "unit_forget_bias", True) else 0.0)
    if class_name in ("Flatten", "Reshape", "InputLayer", "ZeroPadding2D"):
        if class_name == "ZeroPadding2D":
            raise UnsupportedKerasConfigurationException(
                "ZeroPadding2D is not mapped; fold the padding into the "
                "following convolution's padding")
        return None
    raise UnsupportedKerasConfigurationException(
        f"unsupported Keras layer type {class_name!r}")


def _to_output_layer(layer: Layer, loss: LossFunction,
                     fold_activation: Optional[Activation]) -> Layer:
    """Final Dense(+Activation) → OutputLayer / RnnOutput (reference
    KerasModel turns the last Keras layer + training-config loss into a DL4J
    output layer)."""
    if isinstance(layer, DenseLayer) and not isinstance(layer, OutputLayer):
        return OutputLayer(name=layer.name,
                           activation=fold_activation or layer.activation,
                           n_in=layer.n_in, n_out=layer.n_out, loss=loss)
    if isinstance(layer, GravesLSTM):
        raise UnsupportedKerasConfigurationException(
            "recurrent final layer needs a TimeDistributed(Dense) head")
    return layer


# ---------------------------------------------------------------------------
# weight translation


def _lstm_weights(arrays: List[np.ndarray], n_out: int) -> Dict[str, np.ndarray]:
    """Keras LSTM weights → our gate order [i, f, o, g] (g = Keras's c /
    candidate). Keras 1.x: 12 per-gate arrays in order (i, c, f, o); Keras
    2.x: fused kernel/recurrent/bias with column blocks (i, f, c, o)."""
    if len(arrays) == 12:
        (Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo) = arrays
        W = np.concatenate([Wi, Wf, Wo, Wc], axis=1)
        RW = np.concatenate([Ui, Uf, Uo, Uc], axis=1)
        b = np.concatenate([bi, bf, bo, bc])
    elif len(arrays) == 3:
        K, R, b2 = arrays
        def reorder(a):
            i, f, c, o = np.split(a, 4, axis=-1)
            return np.concatenate([i, f, o, c], axis=-1)
        W, RW, b = reorder(K), reorder(R), reorder(b2)
    else:
        raise InvalidKerasConfigurationException(
            f"unexpected LSTM weight count {len(arrays)}")
    z = np.zeros((n_out,), W.dtype)
    return {"W": W, "RW": RW, "b": b, "pI": z, "pF": z, "pO": z}


def _conv_kernel(W: np.ndarray, ordering: str) -> np.ndarray:
    """Keras kernel → HWIO. th (Keras 1 channels_first): (out, in, kh, kw);
    tf / Keras 2: already (kh, kw, in, out)."""
    if ordering == "th":
        return np.transpose(W, (2, 3, 1, 0))
    return W


def _dense_after_flatten(W: np.ndarray, pre_shape: Tuple[int, int, int],
                         ordering: str) -> np.ndarray:
    """Fix dense-weight row order when the Keras model flattened a
    channels_first tensor: Keras rows are (C,H,W)-ordered, our
    CnnToFeedForward flattens NHWC → (H,W,C) order."""
    if ordering != "th":
        return W
    h, w, c = pre_shape
    return (W.reshape(c, h, w, -1).transpose(1, 2, 0, 3)
            .reshape(h * w * c, -1))


def translate_layer_weights(layer: Layer, arrays: List[np.ndarray],
                            ordering: str,
                            pre_flatten_shape: Optional[Tuple[int, int, int]]
                            ) -> Tuple[Dict[str, np.ndarray],
                                       Dict[str, np.ndarray]]:
    """→ (params, state) numpy dicts matching `layer.init_params` /
    `init_state` structure."""
    if isinstance(layer, GravesLSTM):
        return _lstm_weights(arrays, layer.n_out), {}
    if isinstance(layer, ConvolutionLayer):
        W, b = arrays
        return {"W": _conv_kernel(W, ordering), "b": b}, {}
    if isinstance(layer, BatchNormalization):
        gamma, beta, mean, var = arrays
        return ({"gamma": gamma, "beta": beta},
                {"mean": mean, "var": np.maximum(var, 0.0)})
    if isinstance(layer, EmbeddingLayer):
        (W,) = arrays
        return {"W": W, "b": np.zeros((W.shape[1],), W.dtype)}, {}
    if isinstance(layer, DenseLayer):
        W, b = arrays
        if pre_flatten_shape is not None:
            W = _dense_after_flatten(W, pre_flatten_shape, ordering)
        return {"W": W, "b": b}, {}
    raise UnsupportedKerasConfigurationException(
        f"no weight translation for layer {layer.TYPE}")


# ---------------------------------------------------------------------------
# HDF5 plumbing


def _read_h5_weights(f) -> "Dict[str, List[np.ndarray]]":
    """model_weights group → {keras_layer_name: [arrays in weight_names
    order]} (skips weightless layers)."""
    g = f["model_weights"] if "model_weights" in f else f
    out: Dict[str, List[np.ndarray]] = {}
    layer_names = [n.decode() if isinstance(n, bytes) else n
                   for n in g.attrs.get("layer_names", list(g.keys()))]
    for lname in layer_names:
        if lname not in g:
            continue
        lg = g[lname]
        wnames = [n.decode() if isinstance(n, bytes) else n
                  for n in lg.attrs.get("weight_names", [])]
        if not wnames:
            continue
        out[lname] = [np.asarray(lg[wn]) for wn in wnames]
    return out


def _json_attr(f, key: str) -> Optional[dict]:
    v = f.attrs.get(key)
    if v is None:
        return None
    if isinstance(v, bytes):
        v = v.decode("utf-8")
    return json.loads(v)


def _loss_from_training_config(f) -> LossFunction:
    tc = _json_attr(f, "training_config")
    if tc and isinstance(tc.get("loss"), str):
        return map_loss(tc["loss"])
    return LossFunction.MCXENT


# ---------------------------------------------------------------------------
# Sequential → MultiLayerConfiguration


class _SeqTranslation:
    def __init__(self):
        self.layers: List[Layer] = []
        self.keras_names: List[Optional[str]] = []  # aligned with layers
        self.input_type: Optional[InputType] = None
        self.ordering: str = "tf"
        # index of the Dense layer that consumes Flatten output (its weight
        # rows may need CHW→HWC permutation for channels_first models)
        self.flatten_dense_idx: Optional[int] = None


def _translate_sequential(layer_cfgs: List[dict]) -> _SeqTranslation:
    tr = _SeqTranslation()
    pending_flatten = False
    for entry in layer_cfgs:
        cls, cfg = entry["class_name"], dict(entry["config"])
        shape = cfg.get("batch_input_shape")
        if shape is not None and tr.input_type is None:
            tr.ordering = _dim_ordering(cfg)
            tr.input_type = _input_type_from_shape(shape[1:], tr.ordering)
        if cls in ("Convolution2D", "Conv2D", "MaxPooling2D",
                   "AveragePooling2D") and _dim_ordering(cfg) == "th":
            tr.ordering = "th"
        layer = map_keras_layer(cls, cfg)
        if layer is None:
            if cls == "Flatten":
                pending_flatten = True
            continue
        # pending_flatten survives pass-through layers (Dropout/Activation/
        # BatchNorm) until the Dense that consumes the flattened tensor
        if pending_flatten and isinstance(layer, DenseLayer):
            tr.flatten_dense_idx = len(tr.layers)
            pending_flatten = False
        elif isinstance(layer, (ConvolutionLayer, SubsamplingLayer,
                                GravesLSTM)):
            pending_flatten = False
        tr.layers.append(layer)
        tr.keras_names.append(cfg.get("name"))
    if not tr.layers:
        raise InvalidKerasConfigurationException("empty Keras model config")
    return tr


def _fold_trailing_activation(tr: _SeqTranslation) -> Optional[Activation]:
    """Final standalone Activation folds into the output layer."""
    if len(tr.layers) >= 2 and isinstance(tr.layers[-1], ActivationLayer) \
            and isinstance(tr.layers[-2], DenseLayer):
        act_layer = tr.layers.pop()
        tr.keras_names.pop()
        return act_layer.activation
    return None


def _build_mlc(tr: _SeqTranslation, loss: LossFunction) -> MultiLayerConfiguration:
    fold = _fold_trailing_activation(tr)
    tr.layers[-1] = _to_output_layer(tr.layers[-1], loss, fold)
    lb = NeuralNetConfiguration.Builder().list()
    for layer in tr.layers:
        lb.layer(layer)
    if tr.input_type is not None:
        lb.set_input_type(tr.input_type)
    return lb.build()


# ---------------------------------------------------------------------------
# functional Model → ComputationGraphConfiguration


_MERGE_MODES = {"concat": None, "sum": ElementWiseOp.ADD,
                "mul": ElementWiseOp.PRODUCT, "ave": ElementWiseOp.AVERAGE,
                "max": ElementWiseOp.MAX}


def _translate_functional(config: dict, loss: LossFunction) -> Tuple[
        "GraphBuilder", List[str], Dict[str, Layer], str]:
    """Keras functional-API graph → GraphBuilder. Returns (builder,
    output names, {keras_name: our layer}, dim ordering)."""
    gb = NeuralNetConfiguration.Builder().graph_builder()
    name_to_layer: Dict[str, Layer] = {}
    inputs: List[str] = []
    input_types: List[InputType] = []
    out_names = [o[0] for o in config["output_layers"]]
    in_names = {i[0] for i in config["input_layers"]}
    # Keras 1 declares dim_ordering on conv/pool layers, not the InputLayer —
    # scan first so input shapes are interpreted with the right ordering
    ordering = "tf"
    for entry in config["layers"]:
        if entry["class_name"] in ("Convolution2D", "Conv2D", "MaxPooling2D",
                                   "AveragePooling2D") \
                and _dim_ordering(entry["config"]) == "th":
            ordering = "th"

    for entry in config["layers"]:
        cls, cfg = entry["class_name"], dict(entry["config"])
        name = entry.get("name") or cfg.get("name")
        inbound = [n[0] for node in entry.get("inbound_nodes", [])
                   for n in node]
        if cls == "InputLayer" or name in in_names:
            shape = cfg.get("batch_input_shape")
            if shape is None:
                raise InvalidKerasConfigurationException(
                    f"input layer {name!r} missing batch_input_shape")
            inputs.append(name)
            input_types.append(_input_type_from_shape(shape[1:], ordering))
            continue
        if cls in ("Merge", "Concatenate", "Add", "Multiply", "Average",
                   "Maximum"):
            mode = cfg.get("mode", {"Concatenate": "concat", "Add": "sum",
                                    "Multiply": "mul", "Average": "ave",
                                    "Maximum": "max"}.get(cls, "concat"))
            op = _MERGE_MODES.get(mode, "missing")
            if op == "missing":
                raise UnsupportedKerasConfigurationException(
                    f"unsupported Merge mode {mode!r}")
            vertex = MergeVertex() if op is None else ElementWiseVertex(op=op)
            gb.add_vertex(name, vertex, *inbound)
            continue
        layer = map_keras_layer(cls, cfg)
        if layer is None:
            raise UnsupportedKerasConfigurationException(
                f"{cls} inside a functional model is not yet mapped "
                "(Sequential import handles Flatten via auto-preprocessors)")
        if name in out_names:
            layer = _to_output_layer(layer, loss, None)
        layer.name = name
        gb.add_layer(name, layer, *inbound)
        name_to_layer[name] = layer

    gb.add_inputs(*inputs)
    if input_types:
        gb.set_input_types(*input_types)
    gb.set_outputs(*out_names)
    return gb, out_names, name_to_layer, ordering


# ---------------------------------------------------------------------------
# public entry points (reference KerasModelImport.java)


class KerasModelImport:
    """Entry points mirroring the reference `KerasModelImport` API."""

    # -- Sequential --------------------------------------------------------
    @staticmethod
    def import_keras_sequential_configuration(
            model_json: Union[str, Path]) -> MultiLayerConfiguration:
        """JSON string or path (no weights) → MultiLayerConfiguration."""
        cfg = _load_model_config_json(model_json)
        if cfg["class_name"] != "Sequential":
            raise InvalidKerasConfigurationException(
                f"not a Sequential model: {cfg['class_name']}")
        tr = _translate_sequential(_seq_layer_list(cfg))
        return _build_mlc(tr, LossFunction.MCXENT)

    @staticmethod
    def import_keras_sequential_model_and_weights(
            h5_path: Union[str, Path], enforce_training_config: bool = False):
        """HDF5 (config+weights) → initialized MultiLayerNetwork."""
        import h5py

        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with h5py.File(h5_path, "r") as f:
            cfg = _json_attr(f, "model_config")
            if cfg is None:
                raise InvalidKerasConfigurationException(
                    "HDF5 file has no model_config attribute")
            if cfg["class_name"] != "Sequential":
                raise InvalidKerasConfigurationException(
                    f"not a Sequential model: {cfg['class_name']}")
            loss = _loss_from_training_config(f)
            if enforce_training_config and _json_attr(f, "training_config") is None:
                raise InvalidKerasConfigurationException(
                    "no training_config in HDF5 file")
            tr = _translate_sequential(_seq_layer_list(cfg))
            mlc = _build_mlc(tr, loss)
            weights = _read_h5_weights(f)

        net = MultiLayerNetwork(mlc)
        net.init()
        _copy_sequential_weights(net, tr, weights)
        return net

    # -- functional Model --------------------------------------------------
    @staticmethod
    def import_keras_model_configuration(model_json: Union[str, Path]):
        cfg = _load_model_config_json(model_json)
        if cfg["class_name"] not in ("Model", "Functional"):
            raise InvalidKerasConfigurationException(
                f"not a functional Model: {cfg['class_name']}")
        gb, _, _, _ = _translate_functional(cfg["config"], LossFunction.MCXENT)
        return gb.build()

    @staticmethod
    def import_keras_model_and_weights(h5_path: Union[str, Path]):
        """HDF5 functional model → initialized ComputationGraph."""
        import h5py

        from deeplearning4j_tpu.nn.graph.computation_graph import (
            ComputationGraph,
        )

        with h5py.File(h5_path, "r") as f:
            cfg = _json_attr(f, "model_config")
            if cfg is None:
                raise InvalidKerasConfigurationException(
                    "HDF5 file has no model_config attribute")
            if cfg["class_name"] not in ("Model", "Functional"):
                raise InvalidKerasConfigurationException(
                    f"not a functional Model: {cfg['class_name']}")
            loss = _loss_from_training_config(f)
            gb, _, name_to_layer, ordering = _translate_functional(
                cfg["config"], loss)
            weights = _read_h5_weights(f)

        cgc = gb.build()
        net = ComputationGraph(cgc)
        net.init()
        _copy_graph_weights(net, cgc, name_to_layer, weights, ordering)
        return net


def _seq_layer_list(cfg: dict) -> List[dict]:
    c = cfg["config"]
    if isinstance(c, dict):  # Keras 2.x: {"layers": [...], ...}
        return c["layers"]
    return c  # Keras 1.x: bare list


def _load_model_config_json(src: Union[str, Path]) -> dict:
    s = str(src)
    if s.lstrip().startswith("{"):
        return json.loads(s)
    return json.loads(Path(src).read_text(encoding="utf-8"))


# ---------------------------------------------------------------------------
# weight copy into initialized nets


def _keras_weight_key(keras_name: Optional[str], idx: int,
                      weights: Dict[str, List[np.ndarray]]) -> Optional[str]:
    if keras_name and keras_name in weights:
        return keras_name
    return None


def _copy_sequential_weights(net, tr: _SeqTranslation,
                             weights: Dict[str, List[np.ndarray]]) -> None:
    import jax.numpy as jnp

    # match param-bearing layers to weight groups: by name when possible,
    # else in declaration order (reference matches strictly by layer name)
    unused = list(weights.items())
    pre_flatten_shapes = _pre_flatten_shapes(net, tr)
    for i, layer in enumerate(net.layers):
        if not layer.has_params:
            continue
        key = _keras_weight_key(tr.keras_names[i], i, weights)
        if key is not None:
            arrays = weights[key]
            unused = [(k, v) for k, v in unused if k != key]
        elif unused:
            _, arrays = unused.pop(0)
        else:
            raise InvalidKerasConfigurationException(
                f"no weights found for layer {i} ({layer.TYPE})")
        params, state = translate_layer_weights(
            layer, arrays, tr.ordering, pre_flatten_shapes.get(i))
        _check_and_set(net._params[i], params, i, layer)
        for k, v in state.items():
            net._layer_state[i][k] = jnp.asarray(v, jnp.float32)


def _pre_flatten_shapes(net, tr: _SeqTranslation) -> Dict[int, Tuple[int, int, int]]:
    """{dense-layer idx: (H, W, C) of the conv tensor it flattened}."""
    out: Dict[int, Tuple[int, int, int]] = {}
    i = tr.flatten_dense_idx
    if i is None:
        return out
    from deeplearning4j_tpu.nn.conf.inputs import InputTypeConvolutional
    # recover the last convolutional tensor shape before the dense layer
    for j in range(i - 1, -1, -1):
        pt = net.layers[j].output_type(net._input_types[j])
        if isinstance(pt, InputTypeConvolutional):
            out[i] = (pt.height, pt.width, pt.channels)
            break
    else:  # Flatten directly after the network input
        it0 = net.conf.input_type
        if isinstance(it0, InputTypeConvolutional):
            out[i] = (it0.height, it0.width, it0.channels)
    return out


def _check_and_set(param_dict, new_params: Dict[str, np.ndarray], idx,
                   layer) -> None:
    import jax.numpy as jnp

    for k, v in new_params.items():
        if k not in param_dict:
            raise InvalidKerasConfigurationException(
                f"layer {idx} ({layer.TYPE}) has no param {k!r}")
        want = tuple(param_dict[k].shape)
        got = tuple(np.shape(v))
        if want != got:
            raise InvalidKerasConfigurationException(
                f"layer {idx} ({layer.TYPE}) param {k!r}: Keras weight shape "
                f"{got} != expected {want}")
        param_dict[k] = jnp.asarray(v, param_dict[k].dtype)


def _copy_graph_weights(net, cgc, name_to_layer: Dict[str, Layer],
                        weights: Dict[str, List[np.ndarray]],
                        ordering: str = "tf") -> None:
    import jax.numpy as jnp

    for name, layer in name_to_layer.items():
        if not layer.has_params:
            continue
        if name not in weights:
            raise InvalidKerasConfigurationException(
                f"no weights for layer {name!r}")
        params, state = translate_layer_weights(layer, weights[name],
                                                ordering, None)
        _check_and_set(net._params[name], params, name, layer)
        for k, v in state.items():
            net._layer_state[name][k] = jnp.asarray(v, jnp.float32)
