from deeplearning4j_tpu.modelimport.keras import (
    InvalidKerasConfigurationException,
    KerasModelImport,
    UnsupportedKerasConfigurationException,
)

__all__ = [
    "KerasModelImport",
    "InvalidKerasConfigurationException",
    "UnsupportedKerasConfigurationException",
]
