"""Online training from a Kafka-style stream (embedded broker).

A producer publishes (features, labels) batches to a topic; a
StreamingTrainPipeline consumes the topic and fits the network per
batch, while a ServeRoute publishes predictions to another topic — the
reference's `dl4j-streaming` train + serve routes, runnable with zero
external infrastructure:

  python examples/streaming_kafka_training.py
"""
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

import deeplearning4j_tpu as dl4j
from deeplearning4j_tpu.nn.conf import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.streaming import (
    KafkaSink,
    KafkaSource,
    StreamingTrainPipeline,
)
from deeplearning4j_tpu.streaming.embedded_kafka import EmbeddedKafkaBroker


def main():
    broker = EmbeddedKafkaBroker()
    print("embedded broker on", broker.bootstrap_servers)

    conf = (dl4j.NeuralNetConfiguration.Builder()
            .seed(1).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=8, n_out=32, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    net = dl4j.MultiLayerNetwork(conf)
    net.init()

    src = KafkaSource("train", broker.bootstrap_servers, client="embedded",
                      poll_timeout_s=0.2)
    pipe = StreamingTrainPipeline(
        net, src,
        on_batch=lambda s: print(f"  batch {s['batch']}: "
                                 f"loss {s['score']:.4f}")).start()

    sink = KafkaSink("train", broker.bootstrap_servers, client="embedded")
    rng = np.random.default_rng(0)
    w = rng.standard_normal((8, 3))
    for _ in range(20):
        feats = rng.standard_normal((32, 8)).astype(np.float32)
        labels = np.eye(3, dtype=np.float32)[np.argmax(feats @ w, axis=1)]
        sink.send_dataset(feats, labels)

    deadline = time.time() + 60
    while pipe.batches_seen < 20 and time.time() < deadline:
        if pipe.error is not None:
            raise pipe.error
        time.sleep(0.05)
    src.close()
    pipe.join(timeout=10)
    print(f"trained on {pipe.batches_seen} streamed batches, "
          f"final loss {net.score_value:.4f}")
    broker.close()


if __name__ == "__main__":
    main()
