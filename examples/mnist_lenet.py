"""LeNet on MNIST — the canonical first example (BASELINE config 1).

Run: python examples/mnist_lenet.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from deeplearning4j_tpu.datasets.fetchers import MnistDataSetIterator
from deeplearning4j_tpu.models.lenet import lenet_configuration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener


def main():
    net = MultiLayerNetwork(lenet_configuration(learning_rate=0.02))
    net.init()
    net.set_listeners(ScoreIterationListener(10))
    net.fit(MnistDataSetIterator(batch_size=128, num_examples=12800), epochs=3)
    ev = net.evaluate(MnistDataSetIterator(256, num_examples=2560, train=False))
    print(f"accuracy: {ev.accuracy():.3f}  f1: {ev.f1():.3f}")
    print(ev.confusion_matrix)


if __name__ == "__main__":
    main()
