"""Train, freeze to StableHLO, and serve without the framework.

`util/stablehlo_export.export_inference` lowers a trained network's
forward pass — parameters, device-side normalizer, and mixed-precision
casts baked in — to one portable serialized StableHLO blob
(`jax.export`). The serving side needs only the blob: no network
object, no config JSON, no checkpoint, no pickle. With
`platforms=("tpu", "cpu")` the same artifact runs on either backend.

Run: python examples/serving_stablehlo.py
"""
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

import deeplearning4j_tpu as dl4j
from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.util.stablehlo_export import (
    export_inference,
    load_inference,
)


def main():
    # train a small classifier on the committed real digit scans
    conf = (dl4j.NeuralNetConfiguration.Builder().seed(7).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_out=64, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10, activation=Activation.SOFTMAX))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    net = dl4j.MultiLayerNetwork(conf)
    net.init()
    train = DigitsDataSetIterator(batch_size=128)
    for _ in range(20):
        net.fit(train)
    test = DigitsDataSetIterator(batch_size=256, train=False)
    print("trained; held-out accuracy:",
          round(net.evaluate(test).accuracy(), 3))

    # freeze: one blob, the (B, 8, 8, 1) wire shape and the flattening
    # preprocessor baked inside
    test.reset()
    example = next(test).features[:8]
    path = pathlib.Path(tempfile.mkdtemp()) / "digits.stablehlo"
    blob = export_inference(net, example, path=str(path))
    print(f"exported {len(blob):,} bytes -> {path}")

    # "another process": nothing but the file
    serve = load_inference(path)
    probs = serve(example)
    print("served predictions:", np.argmax(probs, axis=1))
    np.testing.assert_allclose(probs, net.output(example),
                               rtol=1e-5, atol=1e-6)
    print("parity with net.output(): ok")


if __name__ == "__main__":
    main()
