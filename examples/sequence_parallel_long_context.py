"""Long-context GPT training with the TIME axis sharded over the mesh.

SequenceParallelWrapper shards every (B, T, H) activation's T dimension
over the `seq` mesh axis and runs attention as a RING: each device keeps
its query shard resident while K/V shards rotate neighbor-to-neighbor
over ICI (`lax.ppermute`), folding each visiting block into the
flash-attention online-softmax accumulator. Context length then scales
with chip count — the capability the reference caps at truncated BPTT
on one device (`MultiLayerNetwork.doTruncatedBPTT`,
`MultiLayerNetwork.java:1140`).

Composes with a `data` axis for 2-D dp x sp; training matches
single-device runs same-seed (see
tests/test_transformer.py::test_sequence_parallel_gpt_parity).

On a single-chip/CPU machine, emulate a mesh first:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/sequence_parallel_long_context.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.transformer import gpt_configuration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.sequence import SequenceParallelWrapper


def main():
    n = len(jax.devices())
    seq = n if n % 2 else n // 2
    data = n // seq
    mesh = make_mesh({"data": data, "seq": seq})
    print(f"sequence-parallel mesh: {dict(mesh.shape)}")

    # T must divide over the seq axis; every device holds T/seq timesteps
    vocab, T, B = 64, 32 * seq, 4 * data
    conf = gpt_configuration(vocab_size=vocab, d_model=64, n_heads=4,
                             n_layers=2, max_length=T, learning_rate=3e-3)
    net = MultiLayerNetwork(conf)
    net.init()
    spw = SequenceParallelWrapper(net, mesh)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (B, T + 1))
    ds = DataSet(ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32))

    for step in range(10):
        spw.fit(ds)
        print(f"step {step}: loss {net.score_value:.4f}")

    # the trained net serves normally — sampling runs on one device
    out = net.output(ds.features[:2])
    print("output:", out.shape, "(B, T, vocab) log-probs; done")


if __name__ == "__main__":
    main()
