"""Character-level GPT: train a small causal transformer on a text corpus
and sample from it (the long-context flagship; swap in your own file).

Run: python examples/gpt_char_lm.py [path/to/text]
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.models.transformer import gpt_configuration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

DEFAULT_TEXT = ("the quick brown fox jumps over the lazy dog. " * 200)


def main():
    text = (open(sys.argv[1]).read() if len(sys.argv) > 1 else DEFAULT_TEXT)
    chars = sorted(set(text))
    stoi = {c: i for i, c in enumerate(chars)}
    ids = np.array([stoi[c] for c in text], np.int64)

    T, B = 64, 32
    net = MultiLayerNetwork(
        gpt_configuration(vocab_size=len(chars), d_model=128, n_heads=4,
                          n_layers=2, max_length=T, learning_rate=1e-3),
        compute_dtype=jnp.bfloat16)
    net.init()

    rng = np.random.default_rng(0)
    eye = np.eye(len(chars), dtype=np.float32)
    batches = []
    for _ in range(60):
        starts = rng.integers(0, len(ids) - T - 1, B)
        window = np.stack([ids[s:s + T + 1] for s in starts])
        batches.append(DataSet(window[:, :-1].astype(np.float32),
                               eye[window[:, 1:]]))
    net.fit(ListDataSetIterator(batches), epochs=3)
    print(f"final loss: {net.score_value:.3f}")

    # jitted KV-cache sampler: one prefill dispatch + one scanned decode
    # dispatch for the whole generation (vs. one full forward per token)
    from deeplearning4j_tpu.models.transformer import generate

    prompt = np.array([[stoi[c] for c in "the quick"]], np.int32)
    # generate up to the positional-table limit (prompt + new <= max_length)
    out = generate(net, prompt, n_tokens=T - prompt.shape[1],
                   temperature=0.0, include_prompt=True)
    print("sample:", "".join(chars[i] for i in out[0]))


if __name__ == "__main__":
    main()
