"""Multi-chip data+tensor-parallel training with ParallelWrapper.

On a single-chip/CPU machine, emulate a mesh first:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/distributed_training.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

import deeplearning4j_tpu as dl4j
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


def main():
    n = len(jax.devices())
    model_par = 2 if n % 2 == 0 else 1
    mesh = make_mesh({"data": n // model_par, "model": model_par})
    print(f"mesh: {dict(mesh.shape)} over {n} devices")

    conf = (dl4j.NeuralNetConfiguration.Builder()
            .seed(7).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=64, n_out=256, activation=Activation.RELU))
            .layer(OutputLayer(n_in=256, n_out=10,
                               activation=Activation.SOFTMAX))
            .build())
    net = dl4j.MultiLayerNetwork(conf)
    net.init()
    # hidden layer column-sharded, output row-sharded (Megatron pair)
    pw = ParallelWrapper(net, mesh=mesh,
                         param_specs={0: {"W": P(None, "model"),
                                          "b": P("model")},
                                      1: {"W": P("model", None)}})

    rng = np.random.default_rng(0)
    c = rng.integers(0, 10, 4096)
    x = (rng.normal(size=(4096, 64)) * 0.5 + c[:, None] * 0.1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[c]
    batches = [DataSet(x[i:i + 256], y[i:i + 256]) for i in range(0, 4096, 256)]
    pw.fit(ListDataSetIterator(batches), epochs=5)
    print(f"loss: {net.score_value:.4f}")


if __name__ == "__main__":
    main()
