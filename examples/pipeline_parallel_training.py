"""Network-level pipeline-parallel training with PipelineParallelWrapper.

The wrapper partitions a real MultiLayerNetwork's homogeneous trunk into
one stage per device on the `pipe` mesh axis and trains with GPipe
microbatching; head/tail layers stay replicated and results match
single-device training same-seed.

On a single-chip/CPU machine, emulate a mesh first:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/pipeline_parallel_training.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import numpy as np

import deeplearning4j_tpu as dl4j
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline_wrapper import (
    PipelineParallelWrapper,
)


def main():
    n = len(jax.devices())
    mesh = make_mesh({"pipe": n})
    print(f"pipeline mesh: {dict(mesh.shape)}")

    # a deep MLP: layer 0 maps input->width (head, replicated), the next
    # `n` identical layers become one stage each, output layer is the tail
    b = (dl4j.NeuralNetConfiguration.Builder()
         .seed(7).learning_rate(0.05)
         .list()
         .layer(DenseLayer(n_in=20, n_out=64, activation=Activation.TANH)))
    for _ in range(n):
        b = b.layer(DenseLayer(n_out=64, activation=Activation.TANH))
    conf = (b.layer(OutputLayer(n_out=5, activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(20))
            .build())

    net = dl4j.MultiLayerNetwork(conf)
    net.init()
    pw = PipelineParallelWrapper(net, mesh)
    print(f"stages: layers [{pw.trunk_start}, {pw.trunk_end}) -> "
          f"{pw.n_stages} x {pw.layers_per_stage}")

    rng = np.random.default_rng(0)
    x = rng.standard_normal((512, 20)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 512)]
    batches = [DataSet(x[i:i + 64], y[i:i + 64]) for i in range(0, 512, 64)]
    for epoch in range(5):
        pw.fit(ListDataSetIterator(batches, batch_size=64))
        print(f"epoch {epoch}: loss {net.score_value:.4f}")

    # after fit() the wrapper has synced params back: the net evaluates
    # and saves exactly like a single-device model
    out = net.output(x[:8])
    print("predictions shape:", out.shape)


if __name__ == "__main__":
    main()
