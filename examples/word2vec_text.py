"""Word2Vec skip-gram with negative sampling (BASELINE config 4).

Run: python examples/word2vec_text.py [corpus.txt]
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import sys

from deeplearning4j_tpu.nlp.word2vec import Word2Vec

DEFAULT = ["the king rules the castle", "the queen rules the castle",
           "a dog chases the cat", "a cat chases the mouse",
           "the king and the queen dance"] * 50


def main():
    if len(sys.argv) > 1:
        with open(sys.argv[1]) as f:
            corpus = [ln.strip() for ln in f if ln.strip()]
    else:
        corpus = DEFAULT
    w2v = Word2Vec(layer_size=64, window=3, negative=5, epochs=5,
                   min_word_frequency=2, seed=42)
    w2v.fit(corpus)
    for w in ("king", "dog"):
        if w in w2v.vocab.words():
            print(w, "->", w2v.words_nearest(w, 4))


if __name__ == "__main__":
    main()
