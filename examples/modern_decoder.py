"""The llama-style decoder stack: RoPE + grouped-query attention + SwiGLU.

Three knobs on the same `gpt_configuration` builder:
- `rope=True`        — rotary relative positions, NO learned positional
                       table, so the trained context length is not a
                       hard limit (demonstrated below);
- `n_kv_heads=2`     — grouped-query attention: `generate()`'s KV caches
                       shrink by n_heads/n_kv_heads (measured +54%
                       decode throughput at 8->2 heads on v5e);
- `ffn_activation="swiglu"` — gated FFN.

With >= 2 devices the script also PIPELINE-trains the same decoder with
dropout=0.1 through `PipelineParallelWrapper` and checks same-seed parity
vs a single-device run — dropout composes with the pipeline because masks
are drawn per GLOBAL batch row (`ops/rng_rows`), so every microbatch
reproduces exactly the rows a single device would draw.

Run: python examples/modern_decoder.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.transformer import generate, gpt_configuration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

DEFAULT_TEXT = ("the quick brown fox jumps over the lazy dog. " * 200)


def main():
    text = DEFAULT_TEXT
    chars = sorted(set(text))
    stoi = {c: i for i, c in enumerate(chars)}
    ids = np.array([stoi[c] for c in text], np.int64)

    T, B = 48, 32
    net = MultiLayerNetwork(
        gpt_configuration(vocab_size=len(chars), d_model=128, n_heads=8,
                          n_kv_heads=2, rope=True, ffn_activation="swiglu",
                          n_layers=2, max_length=T, learning_rate=1e-3),
        compute_dtype=jnp.bfloat16)
    net.init()
    print(net.summary())

    rng = np.random.default_rng(0)
    for _ in range(150):
        starts = rng.integers(0, len(ids) - T - 1, B)
        w = np.stack([ids[s:s + T + 1] for s in starts])
        net.fit(DataSet(w[:, :-1].astype(np.int32), w[:, 1:].astype(np.int32)))
    print(f"final loss: {net.score_value:.3f}")

    # RoPE has no positional table to outgrow: sample well PAST the
    # trained context length (a learned-table model would raise here)
    prompt = np.array([[stoi[c] for c in "the quick"]], np.int32)
    out = generate(net, prompt, n_tokens=2 * T, temperature=0.0,
                   include_prompt=True)
    print(f"sampled {out.shape[1]} tokens (trained at T={T}):")
    print("".join(chars[i] for i in out[0]))

    pipeline_with_dropout(stoi, ids)


def pipeline_with_dropout(stoi, ids):
    """Pipeline-train the decoder WITH dropout (r5): the trunk stages
    thread per-microbatch PRNG, so a dropout=0.1 llama-style net trains
    through GPipe with exact same-seed parity vs one device."""
    import jax

    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.pipeline_wrapper import (
        PipelineParallelWrapper,
    )

    n_dev = len(jax.devices())
    if n_dev < 2:
        print("(single device: skipping the pipeline+dropout demo — run "
              "under the 8-device CPU mesh to see it)")
        return
    n_pipe = 2
    T, B = 32, 16
    conf = lambda: gpt_configuration(
        vocab_size=len(stoi), d_model=64, n_heads=4, n_kv_heads=2,
        rope=True, ffn_activation="swiglu", n_layers=n_pipe, max_length=T,
        dropout=0.1, learning_rate=1e-3, seed=3)
    rng = np.random.default_rng(1)
    starts = rng.integers(0, len(ids) - T - 1, B)
    w = np.stack([ids[s:s + T + 1] for s in starts])
    ds = DataSet(w[:, :-1].astype(np.int32), w[:, 1:].astype(np.int32))

    ref = MultiLayerNetwork(conf())
    ref.init()
    for _ in range(5):
        ref.fit(ds)

    net = MultiLayerNetwork(conf())
    net.init()
    pw = PipelineParallelWrapper(
        net, make_mesh({"pipe": n_pipe}, devices=jax.devices()[:n_pipe]))
    for _ in range(5):
        pw.fit(ds)
    err = abs(net.score_value - ref.score_value)
    print(f"pipeline+dropout parity: pp loss {net.score_value:.5f} vs "
          f"single-device {ref.score_value:.5f} (|diff| {err:.2e})")
    assert err < 1e-3, "pipeline dropout parity broke"


if __name__ == "__main__":
    main()
