"""Expert-parallel Mixture-of-Experts training as a network feature.

MoELayer(expert_axis="expert") + ParallelWrapper over a {data, expert}
mesh: one expert's weights per device, token dispatch via all_to_all
inside the compiled step — the user API is the same MultiLayerNetwork.

On a single-chip/CPU machine, emulate a mesh first:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/expert_parallel_moe.py
"""
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # some environments register an accelerator plugin at interpreter
    # start; the env var alone doesn't win — pin the platform via config
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import deeplearning4j_tpu as dl4j
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    MoELayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


def main():
    n = len(jax.devices())
    dp = 2 if n % 2 == 0 and n > 2 else 1
    n_experts = n // dp
    mesh = make_mesh({"data": dp, "expert": n_experts})
    print(f"mesh: {dict(mesh.shape)} — {n_experts} experts, "
          f"one per device on the 'expert' axis")

    conf = (dl4j.NeuralNetConfiguration.Builder()
            .seed(7).learning_rate(0.05)
            .list()
            .layer(DenseLayer(n_in=8, n_out=32,
                              activation=Activation.RELU))
            .layer(MoELayer(n_in=32, n_out=32, n_experts=n_experts,
                            capacity_factor=float(2 * n_experts),
                            expert_axis="expert"))   # <- the feature
            .layer(RnnOutputLayer(n_in=32, n_out=4,
                                  activation=Activation.SOFTMAX,
                                  loss=LossFunction.MCXENT))
            .set_input_type(InputType.recurrent(8))
            .build())
    net = dl4j.MultiLayerNetwork(conf)
    net.init()
    pw = ParallelWrapper(net, mesh=mesh)
    print("stacked expert W1 sharding:",
          net._params[1]["W1"].sharding.spec)

    rng = np.random.default_rng(0)
    c = rng.integers(0, 4, (8 * n, 6))
    x = (rng.normal(size=(8 * n, 6, 8)) * 0.3 + c[..., None]).astype(
        np.float32)
    y = np.eye(4, dtype=np.float32)[c]
    for epoch in range(15):
        pw.fit(DataSet(x, y))
    print(f"loss after 15 epochs: {net.score_value:.4f}")

    # the same config runs UNSHARDED anywhere (replicated fallback)
    probs = net.output(x[:2])
    print("inference output:", probs.shape)


if __name__ == "__main__":
    main()
