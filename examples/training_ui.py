"""Training with the web UI attached (reference `UIServer.getInstance()
.attach(...)` flow): browse http://localhost:9000 while it runs.

Run: python examples/training_ui.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

import deeplearning4j_tpu as dl4j
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.stats_listener import StatsListener
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage


def main():
    storage = InMemoryStatsStorage()
    server = UIServer.get_instance()   # port 9000
    server.attach(storage)

    conf = (dl4j.NeuralNetConfiguration.Builder().seed(1).learning_rate(0.05)
            .list().layer(DenseLayer(n_in=20, n_out=64))
            .layer(OutputLayer(n_in=64, n_out=5,
                               activation=Activation.SOFTMAX)).build())
    net = dl4j.MultiLayerNetwork(conf)
    net.init()
    net.set_listeners(StatsListener(storage, report_frequency=5))

    rng = np.random.default_rng(0)
    c = rng.integers(0, 5, 2000)
    x = (rng.normal(size=(2000, 20)) * 0.6 + c[:, None] * 0.2).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[c]
    for epoch in range(50):
        for lo in range(0, 2000, 100):
            net.fit(DataSet(x[lo:lo + 100], y[lo:lo + 100]))
    print(f"done; dashboard at http://localhost:{server.port} — Ctrl-C to exit")
    import threading

    try:
        threading.Event().wait()  # keep the (daemon) UI server alive
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
